"""Request-scoped trace identity and propagation.

The reference system has exactly one timestamp in its whole codebase
(SURVEY §5), so when a request is slow there is nothing to say *where*.
A :class:`TraceContext` names one logical request — a question's
submit→admit→prefill→decode→result-wait, or a document's
extract→deid→index — and rides every boundary that request crosses:

* **same thread**: a ``contextvars.ContextVar`` (``current()``), so
  nested stages pick the trace up implicitly (``runtime/metrics.span``
  records an obs span whenever a context is active);
* **executor threads**: explicit handoff via :meth:`TraceContext.run` /
  :func:`call_in` — ``contextvars`` do NOT cross ``ThreadPoolExecutor``
  submissions by themselves, so the HTTP layer passes the context into
  every ``run_in_executor`` lambda;
* **the batcher worker**: the worker thread serves MANY requests at
  once, so it never uses the context var at all — each queued request
  carries its trace object and the worker records spans on it explicitly
  (``engines/serve.py``);
* **broker messages**: ``headers_of()`` / ``recorder.from_headers()``
  serialize the (trace_id, span_id) pair into message headers that
  survive redelivery and journal replay (``service/broker.py``).

Ids are **deterministic**: a process-scoped monotonic counter under a
settable prefix (``reset_ids``), never wall-clock or ``uuid4`` — the
same workload replayed produces the same id sequence, which is what
makes chaos runs (seeded FaultPlans) diffable across reruns.

PHI policy: trace/span attributes must be **identifiers and sizes
only** (doc ids, token counts, queue depths) — never document or answer
text.  Timelines are exported verbatim by ``/api/trace`` and CI
artifacts, so text in an attribute would be a PHI leak by construction
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import itertools
import threading
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Callable, Dict, Iterator, Optional

TRACE_HEADER = "x-trace-id"
SPAN_HEADER = "x-parent-span"

_CURRENT: ContextVar[Optional["TraceContext"]] = ContextVar(
    "docqa_trace", default=None
)

# deterministic id mint: prefix + monotonic counter (thread-safe: next()
# on itertools.count is atomic at the C level)
_id_lock = threading.Lock()
_id_prefix = "t"
_id_counter = itertools.count(1)


def reset_ids(prefix: str = "t", start: int = 1) -> None:
    """Restart the id sequence (tests / bench determinism)."""
    global _id_prefix, _id_counter
    with _id_lock:
        _id_prefix = prefix
        _id_counter = itertools.count(start)


def next_trace_id() -> str:
    return f"{_id_prefix}-{next(_id_counter):06x}"


class TraceContext:
    """One (trace, current-span) position.  Immutable; child spans make
    new contexts.  ``trace`` is an ``obs.spans.Trace`` (duck-typed here
    to keep this module dependency-free)."""

    __slots__ = ("trace", "span_id")

    def __init__(self, trace: Any, span_id: str) -> None:
        self.trace = trace
        self.span_id = span_id

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    @contextmanager
    def activate(self) -> Iterator["TraceContext"]:
        token = _CURRENT.set(self)
        try:
            yield self
        finally:
            _CURRENT.reset(token)

    def run(self, fn: Callable, *args, **kwargs):
        """Explicit cross-thread handoff: run ``fn`` with this context
        active (the executor-lambda entry point)."""
        with self.activate():
            return fn(*args, **kwargs)


def current() -> Optional[TraceContext]:
    return _CURRENT.get()


def current_trace_id() -> Optional[str]:
    ctx = _CURRENT.get()
    return ctx.trace.trace_id if ctx is not None else None


def call_in(ctx: Optional[TraceContext], fn: Callable, *args, **kwargs):
    """Run ``fn`` under ``ctx`` (or plainly when tracing is off) — the
    one helper the HTTP layer threads through its executor lambdas, so
    a disabled recorder costs a single ``None`` check."""
    if ctx is None:
        return fn(*args, **kwargs)
    return ctx.run(fn, *args, **kwargs)


def headers_of(
    ctx: Optional[TraceContext] = None,
) -> Dict[str, str]:
    """Serialize the context for a broker message (empty when inactive).
    The pair is enough to re-link on the consumer side: the open trace
    is found by id, or a stub trace is adopted after a journal replay
    across a restart (the id still ties the hops together)."""
    ctx = ctx if ctx is not None else _CURRENT.get()
    if ctx is None:
        return {}
    return {TRACE_HEADER: ctx.trace.trace_id, SPAN_HEADER: ctx.span_id}


def event(name: str, **attrs: Any) -> None:
    """Record an instant event on the active span (no-op untraced)."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.trace.add_event(name, span_id=ctx.span_id, **attrs)


def flag(reason: str) -> None:
    """Mark the active trace anomalous (always kept by the recorder)."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.trace.flag(reason)
