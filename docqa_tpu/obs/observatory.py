"""Device observatory: per-stage FLOP/byte cost models, device-time
accounting, and MFU/roofline attribution.

The dispatch spine (``engines/spine.py``) measures WHERE device time
goes; this module says what that time BOUGHT.  Each compiled program is
annotated once with its ``cost_analysis()`` FLOPs / bytes-accessed
(``annotate_lowered`` — jax's lowered-stage estimate, no second
compile), keyed by ``(stage, cost_key)`` where ``cost_key`` is the
shape key the call site already uses (the prefill token budget T, the
decode chunk program, a solo generate's ``(batch, bucket)``).  The
spine then reports every completed item's ``(stage, cost_key,
device_seconds)`` here, so per-stage aggregates carry *issued FLOPs*
next to *measured device time* and

    MFU = flops / device_seconds / peak_flops

is an attribution, not a wall-clock guess.  ``peak_flops`` is resolved
from the real backend when one is attached; CPU smoke runs report
against the projected v5e peak with ``peak_flops_source:
"projected-v5e"`` — the same honesty labeling bench already uses for
HBM (a CPU MFU is a *ratio shape*, not a chip claim).

Stdlib-only like the rest of ``docqa_tpu/obs`` (jax is only touched
lazily inside ``annotate_lowered``/``detect_peak_flops``), so the spine
and telemetry can import it without dragging a backend in.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional

# bf16 peak of the chip the projected numbers target (v5e: 197 TFLOP/s,
# 819 GB/s HBM) — the ridge point flops/bytes = peak_flops/peak_bw
# classifies a program compute- vs memory-bound on the roofline
_V5E_PEAK_FLOPS = 197e12
_V5E_PEAK_BYTES_S = 819e9

_PEAK_BY_BACKEND = {
    # conservative, dense-bf16 numbers; override via DOCQA_PEAK_FLOPS
    "tpu": (_V5E_PEAK_FLOPS, "tpu-v5e-bf16"),
    "gpu": (_V5E_PEAK_FLOPS, "projected-v5e"),
    "cpu": (_V5E_PEAK_FLOPS, "projected-v5e"),
}


def detect_peak_flops() -> Dict[str, Any]:
    """(peak_flops, peak_bytes_s, source) for MFU math.  Env override
    ``DOCQA_PEAK_FLOPS`` (absolute FLOP/s) wins; otherwise the attached
    jax backend picks the row — never raises (obs must not)."""
    env = os.environ.get("DOCQA_PEAK_FLOPS")
    if env:
        try:
            return {
                "peak_flops": float(env),
                "peak_bytes_s": _V5E_PEAK_BYTES_S,
                "peak_flops_source": "env:DOCQA_PEAK_FLOPS",
            }
        except ValueError:
            pass
    backend = "cpu"
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        pass
    peak, source = _PEAK_BY_BACKEND.get(backend, _PEAK_BY_BACKEND["cpu"])
    return {
        "peak_flops": peak,
        "peak_bytes_s": _V5E_PEAK_BYTES_S,
        "peak_flops_source": source,
    }


def parse_cost_analysis(lowered) -> Optional[Dict[str, float]]:
    """``{"flops", "bytes_accessed"}`` from a jax ``Lowered``/``Compiled``
    object's ``cost_analysis()``, or None when the backend offers no
    usable estimate.  The ONE parser (jax returns a bare dict on newer
    versions and a one-element list on older ones) — the compile audit
    and the observatory must never drift on this shape."""
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return None
        flops = float(ca.get("flops", 0.0) or 0.0)
        if flops <= 0.0:
            return None
        return {
            "flops": flops,
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
        }
    except Exception:
        return None


class Observatory:
    """Cost-model registry + per-stage device-time/FLOP aggregates."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (stage, cost_key) -> {"flops": f, "bytes": b}
        self._costs: Dict[Any, Dict[str, float]] = {}
        # stage -> {"calls", "device_s", "flops", "bytes", "uncosted"}
        self._stages: Dict[str, Dict[str, float]] = {}

    # ---- cost registration ---------------------------------------------------

    def annotate(
        self,
        stage: str,
        flops: float,
        bytes_accessed: float = 0.0,
        key: Any = None,
    ) -> None:
        with self._lock:
            self._costs[(stage, key)] = {
                "flops": float(flops),
                "bytes": float(bytes_accessed),
            }

    def annotate_lowered(self, stage: str, lowered, key: Any = None) -> bool:
        """Extract FLOPs/bytes from a jax ``Lowered``/``Compiled``
        object's ``cost_analysis()`` and register them.  Fenced: a
        backend without the estimate returns False, never raises."""
        cost = parse_cost_analysis(lowered)
        if cost is None:
            return False
        self.annotate(stage, cost["flops"], cost["bytes_accessed"], key=key)
        return True

    def cost_of(self, stage: str, key: Any = None) -> Optional[Dict[str, float]]:
        with self._lock:
            c = self._costs.get((stage, key))
            return dict(c) if c else None

    # ---- accounting (called by the spine) ------------------------------------

    def record(self, stage: str, cost_key: Any, device_s: float) -> None:
        """One completed work item.  ``cost_key`` may be a tuple/list of
        keys (a prefill round fetch covering several dispatch groups):
        each key's cost accrues to the stage."""
        keys = (
            list(cost_key)
            if isinstance(cost_key, (list, tuple))
            else [cost_key]
        )
        with self._lock:
            row = self._stages.setdefault(
                stage,
                {"calls": 0, "device_s": 0.0, "flops": 0.0, "bytes": 0.0,
                 "uncosted": 0},
            )
            row["calls"] += 1
            row["device_s"] += max(device_s, 0.0)
            costed = False
            for k in keys:
                c = self._costs.get((stage, k))
                if c is not None:
                    row["flops"] += c["flops"]
                    row["bytes"] += c["bytes"]
                    costed = True
            if not costed:
                row["uncosted"] += 1

    def reset(self) -> None:
        """Zero the aggregates (bench measurement windows); registered
        cost models survive — they describe programs, not traffic."""
        with self._lock:
            self._stages.clear()

    # ---- attribution ---------------------------------------------------------

    def stats(self, peak: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Per-stage MFU / roofline table.  Stages with no registered
        cost report device time only (``mfu: None``) — visible gaps
        beat silently-wrong utilization."""
        peak = peak or detect_peak_flops()
        peak_flops = peak["peak_flops"]
        ridge = peak_flops / max(peak["peak_bytes_s"], 1.0)
        with self._lock:
            rows = {k: dict(v) for k, v in self._stages.items()}
        out: Dict[str, Any] = {"peak": peak, "stages": {}}
        for stage, row in sorted(rows.items()):
            dev = row["device_s"]
            flops = row["flops"]
            entry: Dict[str, Any] = {
                "calls": int(row["calls"]),
                "device_s": round(dev, 6),
                "flops": flops,
                "bytes": row["bytes"],
                "uncosted_calls": int(row["uncosted"]),
                "mfu": None,
                "intensity_flops_per_byte": None,
                "roofline_bound": None,
            }
            if flops > 0.0 and dev > 0.0:
                mfu = flops / dev / peak_flops
                if mfu > 1.0:
                    # physically impossible: the stage's measured device
                    # time under-covers the program's execution (e.g. a
                    # synchronous-dispatch CPU backend runs the compute
                    # inside the DISPATCH call, leaving the fetch ~0).
                    # Report the raw ratio for debugging, never claim it
                    # as utilization.
                    entry["mfu"] = None
                    entry["mfu_raw_invalid"] = round(mfu, 6)
                else:
                    entry["mfu"] = round(mfu, 6)
                if row["bytes"] > 0.0:
                    intensity = flops / row["bytes"]
                    entry["intensity_flops_per_byte"] = round(intensity, 3)
                    entry["roofline_bound"] = (
                        "compute" if intensity >= ridge else "memory"
                    )
            out["stages"][stage] = entry
        return out


DEFAULT_OBSERVATORY = Observatory()
