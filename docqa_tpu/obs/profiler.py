"""On-demand ``jax.profiler`` window + per-stage attribution analysis.

Two tools on top of the span recorder:

* :class:`ProfilerWindow` — start/stop a ``jax.profiler`` trace from
  the HTTP surface (``POST /api/profiler/start|stop``) for the rare
  deep-dive that needs XLA-level detail.  Strictly **jit-exterior**: it
  is only ever invoked from the HTTP layer / scripts, never from traced
  code (the jit-purity rule flags any profiler/span call that leaks into
  a jit root), and one window at a time (starting twice is an error, not
  a nested trace).
* :func:`attribution` — the everyday answer: fold a set of completed
  request timelines into a per-stage table (count / total / p50 / p95 /
  share of wall) with each stage classified **device** or **host** along
  the one-fetch-per-dispatch boundary the serving path already enforces:
  a span that blocks on the single device→host fetch of a dispatch
  (``serve_decode_chunk``, ``fused_query`` …) measures device execution;
  everything else is host time.  ``bench.py rag_load`` prints this
  table, and the "(unattributed)" row makes coverage gaps visible
  instead of silently summing to less than the wall.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional

from docqa_tpu.obs.export import coverage
from docqa_tpu.obs.spans import Trace, percentile_nearest_rank

# Stage → device/host classification along the one-fetch-per-dispatch
# boundary (docs/PERF.md §1): a "device" span's wall time is dominated by
# blocking on the dispatch's single device→host fetch (i.e. device
# execution); a "host" span is pure host work or waiting on host events.
# Add new stages here when instrumenting a new engine path — the
# attribution table labels unknown stages "host" (the conservative read).
DEVICE_STAGES = frozenset(
    {
        "serve_prefill",
        "serve_decode_chunk",
        "encode_batch",
        "fused_query",
        "fused_tiered_query",
        "store_search",
        "store_add",
        "generate",
        "seq2seq_generate",
        "fused_rag_generate",
        "ivf_build",
        "ivf_search",
        "tiered_search",
        "tiered_rebuild",
        "deid_batch",
        "index_batch",
    }
)


def stage_kind(name: str) -> str:
    # "dispatch:<stage>" spans are recorded by the dispatch spine
    # (engines/spine.py) around device work items — device by
    # construction, whatever the stage is called
    if name.startswith("dispatch:"):
        return "device"
    return "device" if name in DEVICE_STAGES else "host"


def attribution(traces: Iterable[Trace]) -> List[Dict[str, Any]]:
    """Per-stage rows over completed traces, sorted by total time desc,
    with an "(unattributed)" row for wall time no span covered.  Share
    is of total request wall (root durations summed), so overlapping
    spans (result-wait over decode chunks) can push the stage SUM past
    100% — share answers "how much wall does this stage touch", not a
    partition; the device/host split plus the unattributed row are the
    partition-style reads."""
    traces = [t for t in traces if t is not None]
    per_stage: Dict[str, List[float]] = {}
    wall_total = 0.0
    covered_total = 0.0
    for trace in traces:
        wall = trace.duration_ms
        wall_total += wall
        covered_total += coverage(trace) * wall
        for sp in trace.snapshot_spans():
            if sp is trace.root:
                continue
            per_stage.setdefault(sp.name, []).append(sp.duration_ms)
    rows: List[Dict[str, Any]] = []
    for name, durs in per_stage.items():
        durs.sort()
        total = sum(durs)
        rows.append(
            {
                "stage": name,
                "kind": stage_kind(name),
                "count": len(durs),
                "total_ms": round(total, 1),
                "mean_ms": round(total / len(durs), 2),
                "p50_ms": round(percentile_nearest_rank(durs, 50), 2),
                "p95_ms": round(percentile_nearest_rank(durs, 95), 2),
                "share_pct": round(100.0 * total / wall_total, 1)
                if wall_total
                else 0.0,
            }
        )
    rows.sort(key=lambda r: -r["total_ms"])
    if wall_total:
        rows.append(
            {
                "stage": "(unattributed)",
                "kind": "host",
                "count": len(traces),
                "total_ms": round(wall_total - covered_total, 1),
                "mean_ms": round(
                    (wall_total - covered_total) / max(len(traces), 1), 2
                ),
                "p50_ms": None,
                "p95_ms": None,
                "share_pct": round(
                    100.0 * (wall_total - covered_total) / wall_total, 1
                ),
            }
        )
    return rows


def format_table(rows: List[Dict[str, Any]]) -> str:
    """Fixed-width text table for bench/script output."""
    header = (
        f"{'stage':<24} {'kind':<6} {'count':>6} {'total_ms':>10} "
        f"{'mean_ms':>8} {'p50_ms':>8} {'p95_ms':>8} {'share%':>7}"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        p50 = "-" if r["p50_ms"] is None else f"{r['p50_ms']:.2f}"
        p95 = "-" if r["p95_ms"] is None else f"{r['p95_ms']:.2f}"
        lines.append(
            f"{r['stage']:<24} {r['kind']:<6} {r['count']:>6} "
            f"{r['total_ms']:>10.1f} {r['mean_ms']:>8.2f} {p50:>8} "
            f"{p95:>8} {r['share_pct']:>7.1f}"
        )
    return "\n".join(lines)


def device_host_split(traces: Iterable[Trace]) -> Dict[str, float]:
    """Aggregate device-ms vs host-ms over the traces (host = wall not
    inside a device-classified span)."""
    device = 0.0
    wall = 0.0
    for trace in traces:
        if trace is None:
            continue
        wall += trace.duration_ms
        for sp in trace.snapshot_spans():
            if sp is not trace.root and stage_kind(sp.name) == "device":
                device += sp.duration_ms
    return {
        "device_ms": round(device, 1),
        "host_ms": round(max(wall - device, 0.0), 1),
        "wall_ms": round(wall, 1),
    }


class ProfilerWindow:
    """One guarded ``jax.profiler`` start/stop window (HTTP-surfaced).

    jax is imported inside the methods so the obs package stays
    importable on hosts without an accelerator stack, and so importing
    obs never pays a jax import."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._logdir: Optional[str] = None

    @property
    def active(self) -> bool:
        with self._lock:
            return self._logdir is not None

    @property
    def logdir(self) -> Optional[str]:
        with self._lock:
            return self._logdir

    def start(self, logdir: Optional[str] = None) -> str:
        import tempfile

        import jax.profiler

        with self._lock:
            if self._logdir is not None:
                raise RuntimeError(
                    f"profiler window already active ({self._logdir})"
                )
            if logdir is None:
                logdir = tempfile.mkdtemp(prefix="docqa_profile_")
            jax.profiler.start_trace(logdir)
            self._logdir = logdir
            return logdir

    def stop(self) -> str:
        import jax.profiler

        with self._lock:
            if self._logdir is None:
                raise RuntimeError("no profiler window active")
            jax.profiler.stop_trace()
            logdir, self._logdir = self._logdir, None
            return logdir


DEFAULT_PROFILER = ProfilerWindow()
