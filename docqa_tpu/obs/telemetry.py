"""docqa-telemetry: fixed-interval time-series rollups of the serving plane.

``runtime/metrics.py`` holds since-boot counters and point-in-time
gauges — enough to say *that* the process shed requests, never *when*.
A replica that degrades over ten minutes, a KV-occupancy creep, or a
p95 that doubles mid-soak is invisible to a snapshot unless someone
polls ``/api/status`` at exactly the right moment (ISSUE 7).  This
module supplies the missing axis:

* :class:`WindowedDigest` — per-histogram rollups: raw samples are
  bucketed into fixed ``interval_s`` windows; each sealed window keeps a
  digest (count / sum / p50 / p95 / p99 / max, plus over-threshold
  counts for SLO math) and recent windows also keep their samples, so
  "p95 *now*" merges the last few minutes instead of averaging all-time
  history (the reservoir-drift bug this replaces — metrics.py used to
  trim its sorted reservoir by dropping an extreme alternately, pulling
  long-running percentiles toward the middle of everything ever seen);
* :class:`TelemetryStore` — named counter/gauge/digest series over one
  shared window clock, pruned to a bounded ring (default 10 s × 360
  points = one hour), exported as JSON by ``GET /api/telemetry`` and as
  Prometheus text by ``GET /metrics`` (``obs/expo.py``);
* :class:`TelemetrySampler` — a background thread that scrapes the live
  serving plane into the store each tick: registry counters/gauges,
  pool replica health + breaker states, queue depth + ``n_admitting``,
  active KV slots per prefill bucket, HBM-resident decode bytes
  (``GenerateEngine.decode_memory_analysis``, refreshed rarely — it
  recompiles), jit program-cache sizes, broker queue/journal depths,
  and flight-recorder open/anomalous counts.  The sampler also drives
  the SLO burn-rate evaluator (``obs/slo.py``) once per tick.

Stdlib-only, same discipline as the rest of ``docqa_tpu/obs`` — jax is
never imported here; device objects are scraped by duck-typing.  All
window arithmetic runs on an injectable monotonic clock (``now_fn``) so
tests can step time explicitly; one wall-clock offset is anchored at
construction for export only, mirroring ``obs/spans.Trace``.

PHI policy: series names and values are identifiers, counts and sizes
only — never document or answer text (``docs/OBSERVABILITY.md``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from docqa_tpu.obs.spans import percentile_nearest_rank

log = logging.getLogger("docqa.telemetry")

# deterministic sample-slot hash for the per-window cap (Knuth
# multiplicative): no RNG, so replayed workloads digest identically
_HASH_MULT = 2654435761


class WindowedDigest:
    """Fixed-interval histogram rollups with bounded memory.

    Retention is two-tier: every sealed window keeps its digest for
    ``points`` windows; the most recent ``sample_windows`` of them also
    keep (sorted) samples so percentiles can be MERGED across windows —
    that merge is what ``Histogram.summary()`` reports as "now".  The
    last sealed digest is additionally kept forever as the stale-idle
    fallback, so a service quiet for an hour still reports its last
    known percentiles instead of NaN.
    """

    def __init__(
        self,
        interval_s: float = 10.0,
        points: int = 360,
        sample_windows: int = 18,
        max_samples_per_window: int = 2048,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.interval_s = float(interval_s)
        self.points = int(points)
        self.sample_windows = int(sample_windows)
        self.max_samples_per_window = int(max_samples_per_window)
        self._now = now_fn
        # wall anchor for export only, never for window math
        self._wall_offset = time.time() - now_fn()
        self._lock = threading.Lock()
        self._thresholds: List[float] = []
        # sealed windows, oldest first: list of digest dicts; entries
        # within sample_windows of the head also carry "_samples"
        self._sealed: List[Dict[str, Any]] = []
        self._last_digest: Optional[Dict[str, Any]] = None
        self._cur_widx: Optional[int] = None
        self._cur_samples: List[float] = []
        self._cur_count = 0
        self._cur_sum = 0.0
        # over-threshold counts kept at OBSERVE time, not derived from
        # the capped sample list: at 2× the per-window sample cap a
        # scan-at-seal would halve the SLO's bad fraction exactly when
        # the overload it guards against is happening
        self._cur_over: Dict[str, int] = {}

    # ---- window clock --------------------------------------------------------

    def _widx(self, now: Optional[float]) -> int:
        t = self._now() if now is None else now
        return int(t // self.interval_s)

    def window_wall_start(self, widx: int) -> float:
        return self._wall_offset + widx * self.interval_s

    def register_threshold(self, threshold_ms: float) -> None:
        """Record over-threshold counts per sealed window from now on —
        the SLO evaluator registers its latency objective here so burn
        rates read pre-counted good/bad events instead of re-scanning
        samples that may already have been dropped."""
        with self._lock:
            if threshold_ms not in self._thresholds:
                self._thresholds.append(threshold_ms)

    # ---- recording -----------------------------------------------------------

    def observe(self, value: float, now: Optional[float] = None) -> None:
        widx = self._widx(now)
        with self._lock:
            self._roll_locked(widx)
            self._cur_count += 1
            self._cur_sum += value
            for t in self._thresholds:
                if value > t:
                    key = _thr_key(t)
                    self._cur_over[key] = self._cur_over.get(key, 0) + 1
            n = self._cur_count
            cap = self.max_samples_per_window
            if len(self._cur_samples) < cap:
                self._cur_samples.append(value)
            else:
                # deterministic overwrite keeps the window's sample set
                # representative without RNG (replay-diffable, like
                # obs trace ids)
                self._cur_samples[(n * _HASH_MULT) % cap] = value

    def _seal_locked(self) -> None:
        """Digest the current window and push it onto the sealed ring."""
        if self._cur_widx is None:
            return
        samples = sorted(self._cur_samples)
        digest: Dict[str, Any] = {
            "widx": self._cur_widx,
            "t_unix": self.window_wall_start(self._cur_widx),
            "count": self._cur_count,
            "sum": self._cur_sum,
            "p50": percentile_nearest_rank(samples, 50),
            "p95": percentile_nearest_rank(samples, 95),
            "p99": percentile_nearest_rank(samples, 99),
            "max": samples[-1] if samples else 0.0,
        }
        if self._thresholds:
            # exact observe-time counts (the sample list is capped)
            digest["over"] = {
                _thr_key(t): self._cur_over.get(_thr_key(t), 0)
                for t in self._thresholds
            }
        digest["_samples"] = samples
        self._sealed.append(digest)
        self._last_digest = digest
        self._cur_samples = []
        self._cur_count = 0
        self._cur_sum = 0.0
        self._cur_over = {}

    def _roll_locked(self, widx: int) -> None:
        if self._cur_widx is None:
            self._cur_widx = widx
            return
        if widx == self._cur_widx:
            return
        if widx < self._cur_widx:
            # clock went backwards between caller's now and ours (racing
            # threads): attribute to the current window, never rewind
            return
        self._seal_locked()
        self._cur_widx = widx
        # prune: bounded digest ring, samples only on the recent tail
        if len(self._sealed) > self.points:
            del self._sealed[: len(self._sealed) - self.points]
        horizon = widx - self.sample_windows
        for d in self._sealed:
            if d["widx"] < horizon and "_samples" in d:
                del d["_samples"]

    def roll(self, now: Optional[float] = None) -> None:
        """Advance the window clock without a sample (sampler tick)."""
        with self._lock:
            self._roll_locked(self._widx(now))

    # ---- queries -------------------------------------------------------------

    def recent_percentiles(
        self, qs: Sequence[float] = (50, 95, 99), now: Optional[float] = None
    ) -> Optional[Dict[str, float]]:
        """Merged percentiles over the sample-retention horizon (current
        window included).  None when no samples are retained — callers
        fall back to :meth:`last_percentiles`."""
        widx = self._widx(now)
        with self._lock:
            self._roll_locked(widx)
            horizon = widx - self.sample_windows
            merged: List[float] = list(self._cur_samples)
            for d in self._sealed:
                if d["widx"] >= horizon and "_samples" in d:
                    merged.extend(d["_samples"])
        if not merged:
            return None
        merged.sort()
        return {f"p{int(q)}": percentile_nearest_rank(merged, q) for q in qs}

    def last_percentiles(self) -> Optional[Dict[str, float]]:
        with self._lock:
            d = self._last_digest
        if d is None:
            return None
        return {"p50": d["p50"], "p95": d["p95"], "p99": d["p99"]}

    def windows(
        self, n: Optional[int] = None, now: Optional[float] = None
    ) -> List[Dict[str, Any]]:
        """Sealed digests oldest-first (samples stripped), plus the
        current partial window last (marked ``"partial": True``)."""
        widx = self._widx(now)
        with self._lock:
            self._roll_locked(widx)
            out = [
                {k: v for k, v in d.items() if k != "_samples"}
                for d in self._sealed
            ]
            if self._cur_count:
                samples = sorted(self._cur_samples)
                cur = {
                    "widx": self._cur_widx,
                    "t_unix": self.window_wall_start(self._cur_widx),
                    "count": self._cur_count,
                    "sum": self._cur_sum,
                    "p50": percentile_nearest_rank(samples, 50),
                    "p95": percentile_nearest_rank(samples, 95),
                    "p99": percentile_nearest_rank(samples, 99),
                    "max": samples[-1] if samples else 0.0,
                    "partial": True,
                }
                if self._thresholds:
                    cur["over"] = {
                        _thr_key(t): self._cur_over.get(_thr_key(t), 0)
                        for t in self._thresholds
                    }
                out.append(cur)
        return out[-n:] if n is not None else out

    def window_counts(
        self,
        n_windows: int,
        threshold_ms: Optional[float] = None,
        now: Optional[float] = None,
    ) -> Dict[str, int]:
        """(total, over-threshold) event counts across the last
        ``n_windows`` windows including the current partial one — the
        SLO evaluator's good/bad input.  ``threshold_ms`` must have been
        registered before the windows of interest sealed."""
        wins = self.windows(now=now)
        widx = self._widx(now)
        lo = widx - n_windows + 1
        total = over = 0
        key = _thr_key(threshold_ms) if threshold_ms is not None else None
        for d in wins:
            if d["widx"] < lo:
                continue
            total += d["count"]
            if key is not None:
                over += d.get("over", {}).get(key, 0)
        return {"total": total, "over": over}


def _thr_key(threshold: float) -> str:
    """Stable string key for a threshold (JSON dict keys)."""
    return f"{threshold:g}"


class TelemetryStore:
    """Named time series sharing one window clock.

    Three kinds:

    * **counter** — the sampler records the live cumulative value each
      tick; a window's point is the DELTA vs the previous retained
      window (a decrease is treated as a process-restart reset, so the
      delta is the new cumulative, never negative);
    * **gauge** — last sample in the window wins;
    * **digest** — a :class:`WindowedDigest` registered by name (the
      metrics histograms register theirs, so ``/api/telemetry`` serves
      the same rollups ``summary()`` reads).
    """

    def __init__(
        self,
        interval_s: float = 10.0,
        points: int = 360,
        now_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.interval_s = float(interval_s)
        self.points = int(points)
        self._now = now_fn
        self._wall_offset = time.time() - now_fn()
        self._lock = threading.Lock()
        # name -> {widx: value}; kinds tracked separately so exposition
        # can render the right Prometheus TYPE line
        self._counters: Dict[str, Dict[int, float]] = {}
        # cumulative value of the most recently PRUNED window per
        # counter, so the oldest retained window's delta stays a real
        # delta after a ring wrap instead of re-baselining to the full
        # cumulative (which would read as a giant spike at the ring's
        # trailing edge)
        self._counter_base: Dict[str, float] = {}
        self._gauges: Dict[str, Dict[int, float]] = {}
        self._digests: Dict[str, WindowedDigest] = {}

    # ---- window clock --------------------------------------------------------

    def _widx(self, now: Optional[float]) -> int:
        t = self._now() if now is None else now
        return int(t // self.interval_s)

    def widx(self, now: Optional[float] = None) -> int:
        """Current window index (the SLO evaluator's clock)."""
        return self._widx(now)

    def window_wall_start(self, widx: int) -> float:
        return self._wall_offset + widx * self.interval_s

    # ---- recording -----------------------------------------------------------

    def record_counter(
        self, name: str, cumulative: float, now: Optional[float] = None
    ) -> None:
        widx = self._widx(now)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[widx] = cumulative
            lo = widx - self.points + 1
            if len(series) > self.points:
                pruned = [k for k in series if k < lo]
                if pruned:
                    self._counter_base[name] = series[max(pruned)]
                for k in pruned:
                    del series[k]

    def record_gauge(
        self, name: str, value: float, now: Optional[float] = None
    ) -> None:
        widx = self._widx(now)
        with self._lock:
            series = self._gauges.setdefault(name, {})
            series[widx] = value
            self._prune_locked(series, widx)

    def register_digest(self, name: str, digest: WindowedDigest) -> None:
        with self._lock:
            self._digests[name] = digest

    def _prune_locked(self, series: Dict[int, float], widx: int) -> None:
        lo = widx - self.points + 1
        if len(series) > self.points:
            for k in [k for k in series if k < lo]:
                del series[k]

    # ---- queries -------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(
                set(self._counters) | set(self._gauges) | set(self._digests)
            )

    def series(
        self, name: str, now: Optional[float] = None
    ) -> Optional[Dict[str, Any]]:
        """One series, JSON-ready: ``{"name", "kind", "interval_s",
        "points": [...]}``.  Counter points carry both the window delta
        and the raw cumulative so consumers can re-derive rates."""
        with self._lock:
            if name in self._digests:
                digest = self._digests[name]
            elif name in self._counters:
                items = sorted(self._counters[name].items())
                points = []
                # the ring's trailing edge re-anchors on the last
                # PRUNED window's cumulative; a first-ever window
                # anchors at zero (its delta is the since-boot count)
                prev: Optional[float] = self._counter_base.get(name)
                for widx, cum in items:
                    if cum < (prev or 0.0):
                        # reset (restart): attribute the new cumulative
                        # — a negative delta would be a lie
                        delta = cum
                    else:
                        delta = cum - (prev or 0.0)
                    points.append(
                        {
                            "widx": widx,
                            "t_unix": self.window_wall_start(widx),
                            "value": delta,
                            "cumulative": cum,
                        }
                    )
                    prev = cum
                return {
                    "name": name,
                    "kind": "counter",
                    "interval_s": self.interval_s,
                    "points": points,
                }
            elif name in self._gauges:
                items = sorted(self._gauges[name].items())
                return {
                    "name": name,
                    "kind": "gauge",
                    "interval_s": self.interval_s,
                    "points": [
                        {
                            "widx": widx,
                            "t_unix": self.window_wall_start(widx),
                            "value": v,
                        }
                        for widx, v in items
                    ],
                }
            else:
                return None
        # digest path runs outside the store lock (digest has its own)
        return {
            "name": name,
            "kind": "histogram",
            "interval_s": digest.interval_s,
            "points": digest.windows(now=now),
        }

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        return {
            "interval_s": self.interval_s,
            "points": self.points,
            "series": {
                name: self.series(name, now=now) for name in self.names()
            },
        }

    def latest_gauge(self, name: str) -> Optional[float]:
        with self._lock:
            series = self._gauges.get(name)
            if not series:
                return None
            return series[max(series)]

    def latest_gauges(self) -> Dict[str, float]:
        """Last sample of every gauge series — the Prometheus renderer's
        scrape surface, so a /metrics hit never materializes full
        counter/digest point lists just to learn their kind."""
        with self._lock:
            return {
                name: series[max(series)]
                for name, series in self._gauges.items()
                if series
            }

    def window_delta(
        self, name: str, n_windows: int, now: Optional[float] = None
    ) -> float:
        """Counter increase over the last ``n_windows`` windows
        (current partial included) — the SLO evaluator's event-count
        input.  Deltas are summed from the series points so restart
        resets stay non-negative."""
        s = self.series(name, now=now)
        if s is None or s["kind"] != "counter":
            return 0.0
        lo = self._widx(now) - n_windows + 1
        return float(
            sum(p["value"] for p in s["points"] if p["widx"] >= lo)
        )


# breaker states as numeric gauges (docs/OBSERVABILITY.md): closed=0,
# half_open=1, open=2 — unknown strings surface as -1 rather than lying
_BREAKER_NUM = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class TelemetrySampler:
    """Background scrape of the live serving plane into a store.

    Everything is duck-typed and every probe is individually fenced: a
    dying replica or a closed broker must never kill the sampler — the
    whole point is observing the system while it misbehaves.  The
    sampler owns NO locks of its own beyond the stop event; it only
    reads brief, already-synchronized surfaces (``pool.status()``,
    ``broker.depth``, registry snapshots), so it can never deadlock a
    drain or rolling restart it happens to observe mid-flight.
    """

    def __init__(
        self,
        store: TelemetryStore,
        registry=None,  # runtime.metrics.MetricsRegistry (duck-typed)
        batcher=None,  # EnginePool or ContinuousBatcher (duck-typed)
        broker=None,
        queues: Sequence[str] = (),
        recorder=None,  # obs.recorder.FlightRecorder
        engine=None,  # GenerateEngine (HBM + jit cache probes)
        slo_evaluator=None,  # obs.slo.BurnRateEvaluator
        spine=None,  # engines.spine.DispatchSpine (duck-typed)
        retrieval=None,  # obs.retrieval_observatory.RetrievalObservatory
        sample_every_s: float = 2.0,
        hbm_refresh_s: float = 600.0,
        extra_probes: Sequence[Callable[[], Dict[str, float]]] = (),
    ) -> None:
        self.store = store
        self.registry = registry
        self.batcher = batcher
        self.broker = broker
        self.queues = tuple(queues)
        self.recorder = recorder
        self.engine = engine
        self.slo_evaluator = slo_evaluator
        self.spine = spine
        self.retrieval = retrieval
        self.sample_every_s = float(sample_every_s)
        self.hbm_refresh_s = float(hbm_refresh_s)
        self.extra_probes = list(extra_probes)
        # first HBM probe a full refresh period AFTER construction: the
        # probe AOT-compiles, and boot is exactly when the serving plane
        # is already compile-storming (warmup + first admissions) — a
        # sampler must observe that storm, never join it
        self._hbm_last: float = time.monotonic()
        self._hbm_bytes: Optional[Dict[str, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0
        # cumulative wall seconds spent inside tick() — bench divides
        # this by the measured window to report the sampler's CPU share
        # against the 2% observability budget
        self.tick_seconds = 0.0
        self._probe_errors: Dict[str, int] = {}

    # ---- lifecycle -----------------------------------------------------------

    def start(self) -> "TelemetrySampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="telemetry-sampler"
        )
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        """Idempotent; joins the thread.  Ticks only read bounded
        surfaces, so the join bound is slack, not load-bearing."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("telemetry sampler still alive after stop()")
            else:
                self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self.tick()
            except Exception:
                # belt-and-braces: individual probes are fenced below;
                # this catches store-level surprises
                log.exception("telemetry tick failed")
            self.tick_seconds += time.perf_counter() - t0
            self._stop.wait(self.sample_every_s)

    # ---- one scrape ----------------------------------------------------------

    def _fenced(self, what: str, fn: Callable[[], None]) -> None:
        try:
            fn()
        except Exception:
            # log the FIRST failure of each probe, then count quietly —
            # a dead replica would otherwise spam one traceback per tick
            n = self._probe_errors.get(what, 0)
            self._probe_errors[what] = n + 1
            if n == 0:
                log.exception("telemetry probe %r failed", what)

    def tick(self, now: Optional[float] = None) -> None:
        self.ticks += 1
        if self.registry is not None:
            self._fenced("registry", lambda: self._scrape_registry(now))
        if self.batcher is not None:
            self._fenced("batcher", lambda: self._scrape_batcher(now))
        if self.broker is not None:
            self._fenced("broker", lambda: self._scrape_broker(now))
        if self.recorder is not None:
            self._fenced("recorder", lambda: self._scrape_recorder(now))
        if self.engine is not None:
            self._fenced("engine", lambda: self._scrape_engine(now))
        if self.spine is not None:
            self._fenced("spine", lambda: self._scrape_spine(now))
        if self.retrieval is not None:
            self._fenced("retrieval", lambda: self._scrape_retrieval(now))
        for probe in self.extra_probes:
            self._fenced(
                getattr(probe, "__name__", "extra"),
                lambda p=probe: self._scrape_extra(p, now),
            )
        if self.slo_evaluator is not None:
            self._fenced("slo", lambda: self.slo_evaluator.evaluate(now=now))

    def _scrape_registry(self, now: Optional[float]) -> None:
        counters, histograms, gauges = self.registry.instruments()
        for name, c in counters.items():
            self.store.record_counter(name, c.value, now=now)
        for name, g in gauges.items():
            self.store.record_gauge(name, g.value, now=now)
        for name, h in histograms.items():
            d = getattr(h, "digest", None)
            if d is not None:
                self.store.register_digest(name, d)
                d.roll(now=now)

    def _scrape_batcher(self, now: Optional[float]) -> None:
        b = self.batcher
        rec = self.store.record_gauge
        rec("serve_queue_depth", float(b.n_queued), now=now)
        rec("serve_active_slots", float(b.n_active), now=now)
        n_admitting = getattr(b, "n_admitting", None)
        if n_admitting is not None:
            rec("serve_admitting", float(n_admitting), now=now)
        occupancy = getattr(b, "kv_block_occupancy", None)
        if occupancy is not None:
            # block-pool occupancy (engines/paged.py): per-token KV HBM
            # accounting at block granularity — the ROADMAP item 1
            # evidence that replaced the per-bucket slot gauges (a slot
            # no longer pins a bucket's worth of HBM for its lifetime)
            occ = occupancy()
            for key in (
                "blocks_total", "blocks_used", "block_size",
                "bytes_per_token", "pool_bytes", "used_bytes",
                "tokens_committed", "utilization",
                # prefix-cache occupancy (docqa-prefix): entries, the
                # blocks the cache pins, and the lifetime hit economics
                "prefix_entries", "prefix_blocks", "prefix_hit_rate",
                "prefix_tokens_avoided",
            ):
                if key in occ:
                    rec(f"serve_kv_{key}", float(occ[key]), now=now)
        qos_status = getattr(b, "qos_status", None)
        if qos_status is not None:
            # multi-tenant QoS (docqa-qos): live deferral flag + class
            # queue depths as gauges; the qos_deferred_* /
            # qos_preempted_* counters ride the registry scrape like
            # every other counter
            q = qos_status()
            if q.get("enabled"):
                rec(
                    "qos_defer_active",
                    1.0 if q.get("defer_active") else 0.0,
                    now=now,
                )
                for cls, n in q.get("queued_by_class", {}).items():
                    rec(f"qos_queued_{cls}", float(n), now=now)
        status = getattr(b, "status", None)
        if status is None:
            return
        st = status()
        self.store.record_gauge(
            "pool_pending", float(st.get("pending", 0)), now=now
        )
        for row in st.get("replicas", ()):
            i = row["replica"]
            rec(
                f"pool_replica{i}_alive",
                1.0 if row.get("worker_alive") else 0.0,
                now=now,
            )
            rec(
                f"pool_replica{i}_breaker",
                _BREAKER_NUM.get(str(row.get("breaker")), -1.0),
                now=now,
            )
            rec(
                f"pool_replica{i}_heartbeat_age_s",
                float(row.get("heartbeat_age_s", 0.0)),
                now=now,
            )
            rec(
                f"pool_replica{i}_queued",
                float(row.get("n_queued", 0)),
                now=now,
            )
            rec(
                f"pool_replica{i}_active",
                float(row.get("n_active", 0)),
                now=now,
            )

    def _scrape_broker(self, now: Optional[float]) -> None:
        for q in self.queues:
            self.store.record_gauge(
                f"broker_depth_{q}", float(self.broker.depth(q)), now=now
            )
            self.store.record_gauge(
                f"broker_in_flight_{q}",
                float(self.broker.in_flight(q)),
                now=now,
            )
            self.store.record_gauge(
                f"broker_dead_letters_{q}",
                float(len(self.broker.dead_letters(q))),
                now=now,
            )

    def _scrape_recorder(self, now: Optional[float]) -> None:
        r = self.recorder
        self.store.record_gauge(
            "trace_open", float(len(r.open_traces())), now=now
        )
        self.store.record_counter(
            "trace_anomalous_total",
            float(getattr(r, "anomalous_total", 0)),
            now=now,
        )

    def _scrape_engine(self, now: Optional[float]) -> None:
        engine = self.engine
        fns = getattr(engine, "_fns", None)
        if fns is not None:
            total = 0
            for fn in list(fns.values()):
                size = getattr(fn, "_cache_size", None)
                if callable(size):
                    total += size()
            self.store.record_gauge(
                "jit_decode_cache_programs", float(total), now=now
            )
        # HBM working set via AOT memory_analysis: each call re-lowers
        # and re-compiles, so this probe runs only every hbm_refresh_s
        # (first probe one period after boot — see __init__) — the
        # bytes only change when the serving shape does.  The cached
        # value is re-recorded each tick so the gauge series stays
        # continuous.
        if self.hbm_refresh_s > 0:
            t = time.monotonic()
            if t - self._hbm_last >= self.hbm_refresh_s:
                self._hbm_last = t
                stats = engine.decode_memory_analysis()
                if stats:
                    self._hbm_bytes = {
                        k: float(v)
                        for k, v in stats.items()
                        if isinstance(v, (int, float))
                    }
        if self._hbm_bytes:
            for k, v in self._hbm_bytes.items():
                self.store.record_gauge(f"hbm_decode_{k}", v, now=now)

    def _scrape_spine(self, now: Optional[float]) -> None:
        """Dispatch-spine series (``dispatch_*``; engines/spine.py):
        live gauges — queue depth, lane occupancy (the runtime value of
        the concurrency bound the stream ledger used to gate
        statically) — plus cumulative per-stage device/queue-wait time
        as counters, so ``/api/telemetry`` serves per-window device-time
        deltas per stage."""
        for name, value in self.spine.telemetry_gauges().items():
            self.store.record_gauge(name, float(value), now=now)
        for name, value in self.spine.telemetry_counters().items():
            self.store.record_counter(name, float(value), now=now)

    def _scrape_retrieval(self, now: Optional[float]) -> None:
        """Retrieval-quality series (``retrieve_recall_*``; obs/
        retrieval_observatory.py): the shadow estimator's live recall
        estimate + Wilson CI bounds, pending shadow depth, and the
        current/recommended nprobe as gauges.  The per-comparison
        counters (``retrieve_shadow_expected``/``_missed`` — the recall
        SLO's ratio inputs) ride the registry scrape like every other
        counter."""
        for name, value in self.retrieval.telemetry_gauges().items():
            self.store.record_gauge(name, float(value), now=now)

    def _scrape_extra(self, probe, now: Optional[float]) -> None:
        for name, value in (probe() or {}).items():
            self.store.record_gauge(name, float(value), now=now)
