"""FlightRecorder: bounded retention of recent request timelines.

A ring buffer of the last N completed traces plus an always-keep ring of
*anomalous* ones — deadline sheds, degraded answers, breaker-open
requests, decode failures, and the slowest percentile by wall time.  The
point is post-hoc diagnosis: when ``rag_load`` sustains 1 qps against a
16 qps target (BENCH_r05), the recorder holds complete per-request
timelines that say which of queue-wait / admit / prefill / decode-chunk
/ result-wait ate the time — dumpable via ``/api/traces`` and
``scripts/trace_dump.py`` without having had profiling enabled ahead of
the incident.

Retention policy:

* ``capacity`` most recent completed traces (everything);
* ``anomalous_capacity`` flagged traces kept SEPARATELY, so a burst of
  healthy traffic cannot evict the one request that shed;
* slowness is a flag too: a completing trace whose duration reaches the
  ``slow_percentile`` of the recent-duration window is flagged
  ``slow_p{N}`` (needs a minimum sample count — the first requests of a
  process are never "slow" by definition);
* open traces are bounded (``max_open``): a trace nobody finishes (a
  crashed consumer, an abandoned stream) is evicted oldest-first with an
  ``abandoned`` flag instead of leaking.

Everything no-ops when disabled (``set_enabled(False)``) — the bench's
tracing-overhead A/B flips exactly this switch.
"""

from __future__ import annotations

import collections
import threading
from contextlib import contextmanager as contextlib_contextmanager
from typing import Any, Dict, List, Optional

from docqa_tpu.obs.context import (
    SPAN_HEADER,
    TRACE_HEADER,
    TraceContext,
    next_trace_id,
)
from docqa_tpu.obs.spans import Trace, percentile_nearest_rank

_enabled = True


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


def enabled() -> bool:
    return _enabled


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 256,
        anomalous_capacity: int = 64,
        slow_percentile: float = 95.0,
        min_slow_samples: int = 20,
        max_open: int = 1024,
    ) -> None:
        self.slow_percentile = slow_percentile
        self.min_slow_samples = min_slow_samples
        self.max_open = max_open
        self._lock = threading.Lock()
        self._open: "collections.OrderedDict[str, Trace]" = (
            collections.OrderedDict()
        )
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._anomalous: collections.deque = collections.deque(
            maxlen=anomalous_capacity
        )
        self._durations: collections.deque = collections.deque(maxlen=512)
        # lifetime count of traces that entered the anomalous ring — the
        # telemetry sampler scrapes this as a counter series, so a burst
        # of anomalies is visible even after the ring itself rotated
        self.anomalous_total = 0

    # ---- trace lifecycle -----------------------------------------------------

    def new_trace(self, name: str, **attrs: Any) -> Optional[TraceContext]:
        if not _enabled:
            return None
        trace = Trace(next_trace_id(), name, attrs=attrs)
        self._register(trace)
        return TraceContext(trace, trace.root.span_id)

    def adopt(self, trace_id: str, name: str) -> TraceContext:
        """Open a trace under a GIVEN id — the cross-restart case: a
        journal-replayed message carries a trace id whose original trace
        object died with the old process.  The stub still links the
        post-replay hops under the same id."""
        trace = Trace(trace_id, name)
        trace.root.attrs["adopted"] = True
        self._register(trace)
        return TraceContext(trace, trace.root.span_id)

    def _register(self, trace: Trace) -> None:
        evicted: List[Trace] = []
        with self._lock:
            self._open[trace.trace_id] = trace
            while len(self._open) > self.max_open:
                _, old = self._open.popitem(last=False)
                evicted.append(old)
        for old in evicted:
            old.flag("abandoned")
            self.complete(old, status="abandoned")

    def from_headers(
        self, headers: Optional[Dict[str, Any]], name: str = "linked"
    ) -> Optional[TraceContext]:
        """Re-attach to the trace a broker message names (or adopt a stub
        for an id we no longer hold).  Returns None when the message
        carries no trace or recording is disabled."""
        if not _enabled or not headers:
            return None
        trace_id = headers.get(TRACE_HEADER)
        if not trace_id:
            return None
        with self._lock:
            trace = self._open.get(trace_id)
        if trace is None:
            return self.adopt(trace_id, name)
        parent = headers.get(SPAN_HEADER) or trace.root.span_id
        return TraceContext(trace, parent)

    def complete(self, trace: Optional[Trace], status: str = "ok") -> None:
        """Finish + retain.  Idempotent: the first completion wins (a
        document trace can be finished by either the pipeline terminal
        status or a dead-letter callback)."""
        if trace is None:
            return
        # cost-record fallback retirement (docqa-costscope): a request
        # whose typed path never retired its record — a 503 the batcher
        # never saw, an exception escaping the HTTP handler — retires
        # here when its trace completes, so no traced request can leak
        # an open record.  Exactly-once: the ledger guards, so the
        # normal typed retirement always wins.
        rec = getattr(trace, "cost_record", None)
        if rec is not None:
            try:
                from docqa_tpu.obs.costs import DEFAULT_COST_LEDGER

                DEFAULT_COST_LEDGER.retire(
                    rec, "ok" if status == "ok" else "error"
                )
            except Exception:
                pass
        if not trace.finish(status):
            with self._lock:
                self._open.pop(trace.trace_id, None)
            return
        dur = trace.duration_ms
        with self._lock:
            self._open.pop(trace.trace_id, None)
            if (
                len(self._durations) >= self.min_slow_samples
                and dur >= self._quantile_locked(self.slow_percentile)
            ):
                # flag() takes the trace's own lock; safe (distinct locks)
                trace.flag(f"slow_p{int(self.slow_percentile)}")
            self._durations.append(dur)
            self._ring.append(trace)
            if trace.flags and not any(
                t is trace for t in self._anomalous
            ):
                # membership check: flag_window() racing this completion
                # may have promoted the trace already — a double insert
                # would evict a real always-keep trace from the ring and
                # over-count anomalous_total during exactly the incident
                # the ring preserves evidence for
                self._anomalous.append(trace)
                self.anomalous_total += 1

    def _quantile_locked(self, q: float) -> float:
        return percentile_nearest_rank(sorted(self._durations), q)

    def flag_window(
        self,
        t_lo_unix: float,
        t_hi_unix: float,
        flag: str,
        names: Optional[List[str]] = None,
    ) -> int:
        """Flag every retained trace that STARTED inside the wall-clock
        window ``[t_lo_unix, t_hi_unix)`` — the SLO burn-rate alert's
        evidence hook (obs/slo.py): "the p95 objective burned between
        14:02:10 and 14:02:30" becomes exactly those timelines in the
        always-keep anomalous ring.  Completed traces that were healthy
        at completion are promoted into the ring here; open traces get
        the flag now and land in the ring at completion as usual.
        Returns the number of traces newly flagged."""
        n = 0
        with self._lock:
            anomalous_ids = {id(t) for t in self._anomalous}
            pools = (
                list(self._open.values())
                + list(self._ring)
                + list(self._anomalous)
            )
            seen: set = set()
            for trace in pools:
                if id(trace) in seen:
                    continue
                seen.add(id(trace))
                if not (t_lo_unix <= trace.wall0 < t_hi_unix):
                    continue
                if names is not None and trace.name not in names:
                    continue
                if flag in trace.flags:
                    continue
                trace.flag(flag)
                n += 1
                if trace.finished and id(trace) not in anomalous_ids:
                    self._anomalous.append(trace)
                    self.anomalous_total += 1
        return n

    # ---- lookup --------------------------------------------------------------

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            if trace_id in self._open:
                return self._open[trace_id]
            for pool in (self._anomalous, self._ring):
                for trace in pool:
                    if trace.trace_id == trace_id:
                        return trace
        return None

    def recent(self, n: int = 50) -> List[Trace]:
        with self._lock:
            return list(self._ring)[-n:][::-1]

    def anomalous(self, n: int = 50) -> List[Trace]:
        with self._lock:
            return list(self._anomalous)[-n:][::-1]

    def open_traces(self) -> List[Trace]:
        with self._lock:
            return list(self._open.values())

    def summaries(
        self, n: int = 50, anomalous: bool = False
    ) -> List[Dict[str, Any]]:
        traces = self.anomalous(n) if anomalous else self.recent(n)
        return [
            {
                "trace_id": t.trace_id,
                "name": t.name,
                "status": t.status,
                "flags": list(t.flags),
                "duration_ms": round(t.duration_ms, 3),
                "n_spans": len(t.snapshot_spans()),
                "started_unix": t.wall0,
            }
            for t in traces
        ]

    def clear(self) -> None:
        with self._lock:
            self._open.clear()
            self._ring.clear()
            self._anomalous.clear()
            self._durations.clear()
            self.anomalous_total = 0


DEFAULT_RECORDER = FlightRecorder()


# ---- module-level conveniences over the default recorder -------------------


def new_trace(name: str, **attrs: Any) -> Optional[TraceContext]:
    return DEFAULT_RECORDER.new_trace(name, **attrs)


def from_headers(
    headers: Optional[Dict[str, Any]], name: str = "linked"
) -> Optional[TraceContext]:
    return DEFAULT_RECORDER.from_headers(headers, name=name)


def finish(ctx: Optional[TraceContext], status: str = "ok") -> None:
    if ctx is not None:
        DEFAULT_RECORDER.complete(ctx.trace, status=status)


@contextlib_contextmanager
def ensure(name: str, **attrs: Any):
    """Yield the ACTIVE context, or open (and activate) a fresh trace for
    the duration — the entry-point idiom for code reachable both from a
    traced HTTP request and directly (scripts, tests, chaos drives)."""
    from docqa_tpu.obs.context import current

    ctx = current()
    if ctx is not None:
        yield ctx
        return
    ctx = new_trace(name, **attrs)
    if ctx is None:
        yield None
        return
    with ctx.activate():
        yield ctx


def finish_id(
    trace_id: Optional[str], status: str = "ok", flag: Optional[str] = None
) -> None:
    """Finish an open trace by id (the pipeline's terminal-status path,
    which holds only the message headers)."""
    if not trace_id:
        return
    trace = DEFAULT_RECORDER.get(trace_id)
    if trace is None or trace.finished:
        return
    if flag:
        trace.flag(flag)
    DEFAULT_RECORDER.complete(trace, status="error" if flag else status)
