"""Host-side span recording: monotonic-clock intervals on one trace.

A :class:`Trace` is a mutable, thread-safe record of one request's
timeline: a root span plus child spans recorded from ANY thread (HTTP
executors, the batcher worker, pipeline consumers).  Two recording
styles, one storage:

* :func:`start_span` — context-manager style for code running *under*
  the request's context var (``runtime/metrics.span`` wraps this, so
  every existing ``span("qa_retrieve")`` site records a trace span for
  free when a trace is active);
* :meth:`Trace.record_span` — explicit (name, t_start, t_end) for the
  batcher worker and pipeline consumers, which serve many requests per
  thread and therefore never touch the context var.

Clocks: span times are ``time.perf_counter()`` (monotonic — the same
clock ``runtime/metrics.span`` uses, so histogram and trace agree to
the microsecond); each trace anchors one wall-clock timestamp at birth
for export.  Spans never call back into jax, metrics, or logging —
recording is list-append under a lock, cheap enough for the decode
path's per-chunk cadence.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import contextlib

from docqa_tpu.obs.context import TraceContext, current


def percentile_nearest_rank(ordered: list, q: float) -> float:
    """Nearest-rank percentile over an already-SORTED sequence — the ONE
    implementation behind the recorder's slow-p95 flagging, the
    attribution table's p50/p95, and the metrics histograms, so the
    three can never disagree about what "p95" means.  Returns 0.0 on
    empty input (callers gate on sample counts themselves)."""
    if not ordered:
        return 0.0
    idx = min(len(ordered) - 1, max(0, round(q / 100 * (len(ordered) - 1))))
    return ordered[idx]


@dataclass
class Span:
    """One timed interval.  ``t_start``/``t_end`` are perf_counter
    values; export converts to trace-relative milliseconds."""

    name: str
    span_id: str
    parent_id: Optional[str]
    t_start: float
    t_end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def duration_ms(self) -> float:
        end = self.t_end if self.t_end is not None else time.perf_counter()
        return (end - self.t_start) * 1000.0


class Trace:
    """All spans of one request.  Thread-safe; completion is idempotent."""

    def __init__(
        self, trace_id: str, name: str, attrs: Optional[Dict[str, Any]] = None
    ) -> None:
        self.trace_id = trace_id
        self.name = name
        self.t0 = time.perf_counter()
        self.wall0 = time.time()  # export anchor only; never used for math
        self.status: Optional[str] = None
        self.flags: List[str] = []
        self._lock = threading.Lock()
        self._span_ids = itertools.count(2)
        self.root = Span(
            name=name, span_id="s1", parent_id=None, t_start=self.t0,
            attrs=dict(attrs or {}),
        )
        self.spans: List[Span] = [self.root]

    # ---- recording -----------------------------------------------------------

    def _new_span_id(self) -> str:
        return f"s{next(self._span_ids)}"

    def start_span(
        self, name: str, parent_id: Optional[str] = None, **attrs: Any
    ) -> Span:
        sp = Span(
            name=name,
            span_id=self._new_span_id(),
            parent_id=parent_id or self.root.span_id,
            t_start=time.perf_counter(),
            attrs=attrs,
        )
        with self._lock:
            self.spans.append(sp)
        return sp

    def end_span(self, span: Span, t_end: Optional[float] = None) -> None:
        span.t_end = t_end if t_end is not None else time.perf_counter()

    def record_span(
        self,
        name: str,
        t_start: float,
        t_end: float,
        parent_id: Optional[str] = None,
        **attrs: Any,
    ) -> Span:
        """Explicit-times recording — the worker-thread API (the batcher
        and pipeline consumers multiplex requests, so the interval is
        measured first and attributed to a request's trace after)."""
        sp = Span(
            name=name,
            span_id=self._new_span_id(),
            parent_id=parent_id or self.root.span_id,
            t_start=t_start,
            t_end=t_end,
            attrs=attrs,
        )
        with self._lock:
            self.spans.append(sp)
        return sp

    def add_event(
        self, name: str, span_id: Optional[str] = None, **attrs: Any
    ) -> None:
        evt = {"name": name, "t": time.perf_counter(), **attrs}
        with self._lock:
            target = self.root
            if span_id is not None:
                for sp in reversed(self.spans):
                    if sp.span_id == span_id:
                        target = sp
                        break
            target.events.append(evt)

    def flag(self, reason: str) -> None:
        with self._lock:
            if reason not in self.flags:
                self.flags.append(reason)

    # ---- completion ----------------------------------------------------------

    def finish(self, status: str = "ok") -> bool:
        """Close the root span; True only the FIRST time (idempotent —
        a trace can reach completion from both the HTTP layer and a
        pipeline terminal-status write)."""
        with self._lock:
            if self.root.t_end is not None:
                return False
            self.root.t_end = time.perf_counter()
            self.status = status
            for sp in self.spans:
                if sp.t_end is None:  # close stragglers at trace end
                    sp.t_end = self.root.t_end
            return True

    @property
    def finished(self) -> bool:
        return self.root.t_end is not None

    @property
    def duration_ms(self) -> float:
        return self.root.duration_ms

    def snapshot_spans(self) -> List[Span]:
        with self._lock:
            return list(self.spans)


@contextlib.contextmanager
def start_span(name: str, **attrs: Any) -> Iterator[Optional[Span]]:
    """Record a child span of the ACTIVE context (no-op when untraced:
    one context-var read).  The body runs with the child as the current
    context, so nested spans parent correctly."""
    ctx = current()
    if ctx is None:
        yield None
        return
    sp = ctx.trace.start_span(name, parent_id=ctx.span_id, **attrs)
    child = TraceContext(ctx.trace, sp.span_id)
    with child.activate():
        try:
            yield sp
        finally:
            ctx.trace.end_span(sp)
