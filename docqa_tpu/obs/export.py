"""Timeline export: JSON and Chrome-trace (Perfetto-loadable) formats.

``timeline_dict`` is the ``/api/trace/<id>`` payload — trace-relative
millisecond spans plus the coverage figure the acceptance contract
gates on (span union over request wall time).  ``to_chrome_trace``
emits the Trace Event Format (``ph: "X"`` complete events, microsecond
timestamps) that https://ui.perfetto.dev and ``chrome://tracing`` load
directly; each trace gets its own ``tid`` row so concurrent requests
stack as parallel tracks.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List

from docqa_tpu.obs.spans import Span, Trace


def _span_dict(trace: Trace, sp: Span) -> Dict[str, Any]:
    end = sp.t_end if sp.t_end is not None else sp.t_start
    return {
        "name": sp.name,
        "span_id": sp.span_id,
        "parent_id": sp.parent_id,
        "start_ms": round((sp.t_start - trace.t0) * 1000.0, 3),
        "end_ms": round((end - trace.t0) * 1000.0, 3),
        "duration_ms": round((end - sp.t_start) * 1000.0, 3),
        "attrs": dict(sp.attrs),
        "events": [
            {
                **{k: v for k, v in evt.items() if k != "t"},
                "t_ms": round((evt["t"] - trace.t0) * 1000.0, 3),
            }
            for evt in sp.events
        ],
    }


def coverage(trace: Trace) -> float:
    """Fraction of the root span's wall time covered by the union of its
    child spans — the "no unattributed gap" acceptance figure.  Child
    intervals are clipped to the root window and merged, so overlapping
    spans (a result-wait spanning decode chunks) count once."""
    spans = trace.snapshot_spans()
    root = trace.root
    root_end = root.t_end if root.t_end is not None else max(
        (s.t_end or s.t_start for s in spans), default=root.t_start
    )
    total = root_end - root.t_start
    if total <= 0:
        return 1.0
    intervals = []
    for sp in spans:
        if sp is root:
            continue
        lo = max(sp.t_start, root.t_start)
        hi = min(sp.t_end if sp.t_end is not None else root_end, root_end)
        if hi > lo:
            intervals.append((lo, hi))
    if not intervals:
        return 0.0
    intervals.sort()
    covered = 0.0
    cur_lo, cur_hi = intervals[0]
    for lo, hi in intervals[1:]:
        if lo > cur_hi:
            covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    covered += cur_hi - cur_lo
    return min(covered / total, 1.0)


def timeline_dict(trace: Trace) -> Dict[str, Any]:
    spans = trace.snapshot_spans()
    out = {
        "trace_id": trace.trace_id,
        "name": trace.name,
        "status": trace.status,
        "flags": list(trace.flags),
        "started_unix": trace.wall0,
        "duration_ms": round(trace.duration_ms, 3),
        "coverage": round(coverage(trace), 4),
        "spans": [_span_dict(trace, sp) for sp in spans],
    }
    # per-request cost summary (docqa-costscope): attached by the
    # ledger at retirement — class, outcome, device-ms split, KV
    # block-seconds.  Absent on unaccounted traces.
    cost = getattr(trace, "cost_summary", None)
    if cost is not None:
        out["cost"] = cost
    return out


def to_chrome_trace(traces: Iterable[Trace]) -> Dict[str, Any]:
    traces = list(traces)
    if not traces:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(t.t0 for t in traces)
    events: List[Dict[str, Any]] = []
    for tid, trace in enumerate(traces, start=1):
        events.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 1,
                "tid": tid,
                "args": {"name": f"{trace.name} {trace.trace_id}"},
            }
        )
        cost = getattr(trace, "cost_summary", None)
        if cost is not None:
            # the cost vector as an instant event at trace start: shows
            # up in Perfetto's args pane without inventing counter rows
            events.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": "cost_summary",
                    "pid": 1,
                    "tid": tid,
                    "ts": round((trace.t0 - base) * 1e6, 1),
                    "args": dict(cost),
                }
            )
        for sp in trace.snapshot_spans():
            end = sp.t_end if sp.t_end is not None else sp.t_start
            events.append(
                {
                    "ph": "X",
                    "name": sp.name,
                    "cat": trace.name,
                    "pid": 1,
                    "tid": tid,
                    "ts": round((sp.t_start - base) * 1e6, 1),
                    "dur": round((end - sp.t_start) * 1e6, 1),
                    "args": {
                        "trace_id": trace.trace_id,
                        "span_id": sp.span_id,
                        "parent_id": sp.parent_id,
                        **sp.attrs,
                    },
                }
            )
            for evt in sp.events:
                events.append(
                    {
                        "ph": "i",
                        "s": "t",
                        "name": evt["name"],
                        "pid": 1,
                        "tid": tid,
                        "ts": round((evt["t"] - base) * 1e6, 1),
                        "args": {
                            k: v
                            for k, v in evt.items()
                            if k not in ("name", "t")
                        },
                    }
                )
    return {"traceEvents": events, "displayTimeUnit": "ms"}
