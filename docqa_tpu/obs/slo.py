"""SLO objectives and multi-window burn-rate alerting over telemetry.

An SLO here is a budgeted promise about /ask (``docs/OBSERVABILITY.md``
"Time series, SLOs, and /metrics"): availability (non-5xx fraction),
p95 latency (fraction of requests under a threshold), and degraded-
answer rate (the PR-1 extractive fallback is an availability save but a
quality spend — it gets its own budget).  Point-in-time error RATES
page on blips and miss slow leaks; **burn rate** — how fast the error
budget is being consumed relative to plan — is the standard fix
(Google SRE workbook ch. 5): burn 1.0 spends exactly the budget over
the objective period; burn 14 exhausts a month's budget in ~2 days.

Evaluation is **multi-window**: an alert fires only when BOTH a short
window (fast detection, noisy alone) and a long window (evidence the
burn is sustained, slow alone) exceed ``burn_threshold``.  Windows are
counted in telemetry rollup windows (``TelemetryStore.interval_s``), so
the same config serves a 10 s production cadence and a 100 ms test
cadence.

Firing closes the loop to evidence: the evaluator flags the firing
window's traces **anomalous in the flight recorder** — the always-keep
ring — so ``/api/traces?anomalous=1`` answers "SLO burning" with the
exact request timelines that burned it, and ``/api/status`` carries the
live alert state (docs/OPERATIONS.md "Respond to a burn-rate alert").

Stdlib-only; all inputs come from :class:`~docqa_tpu.obs.telemetry.
TelemetryStore` series and metrics-histogram windowed digests.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from docqa_tpu.obs.telemetry import TelemetryStore


@dataclass(frozen=True)
class SLODef:
    """One objective.  ``kind``:

    * ``"latency"`` — good = samples of ``digest_name`` at or under
      ``threshold_ms``; ``objective`` is the good fraction (0.95 = a
      p95 objective by construction);
    * ``"ratio"`` — good = 1 − ``bad_series``/``total_series`` counter
      deltas; covers availability (bad = 5xx) and degraded-answer rate
      (bad = ``qa_degraded``) alike; ``objective`` is the good fraction.
    """

    name: str
    kind: str  # "latency" | "ratio"
    objective: float
    total_series: str = ""
    bad_series: str = ""
    digest_name: str = ""
    threshold_ms: float = 0.0
    short_windows: int = 2
    long_windows: int = 30
    burn_threshold: float = 4.0
    clear_windows: int = 3
    # traffic floor: burn math over a handful of events is noise — below
    # this many events in the window, the window reads as not burning
    min_events: int = 6
    # which trace names the firing window flags anomalous (empty = all)
    trace_names: Tuple[str, ...] = ()

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)


def default_ask_slos(
    p95_objective_ms: float,
    availability: float = 0.99,
    degraded_budget: float = 0.05,
    short_windows: int = 2,
    long_windows: int = 30,
    burn_threshold: float = 4.0,
) -> List[SLODef]:
    """The /ask objectives the runtime serves by default (ISSUE 7):
    availability, p95 latency, degraded-answer rate.  ``ask_requests``/
    ``ask_failures`` are stamped by ``service/app.py`` at the one /ask
    response point; ``qa_degraded`` already exists (PR 1)."""
    ask_traces = ("ask", "ask_stream")
    return [
        SLODef(
            name="ask_availability",
            kind="ratio",
            objective=availability,
            total_series="ask_requests",
            bad_series="ask_failures",
            short_windows=short_windows,
            long_windows=long_windows,
            burn_threshold=burn_threshold,
            trace_names=ask_traces,
        ),
        SLODef(
            name="ask_p95_latency",
            kind="latency",
            objective=0.95,
            digest_name="qa_e2e_ms",
            threshold_ms=p95_objective_ms,
            short_windows=short_windows,
            long_windows=long_windows,
            burn_threshold=burn_threshold,
            trace_names=ask_traces,
        ),
        SLODef(
            name="ask_degraded_rate",
            kind="ratio",
            objective=1.0 - degraded_budget,
            total_series="ask_requests",
            bad_series="qa_degraded",
            short_windows=short_windows,
            long_windows=long_windows,
            burn_threshold=burn_threshold,
            trace_names=ask_traces,
        ),
    ]


def default_retrieval_slos(
    recall_target: float = 0.95,
    short_windows: int = 2,
    long_windows: int = 30,
    burn_threshold: float = 4.0,
    min_events: int = 6,
) -> List[SLODef]:
    """The retrieval-quality objective (docqa-recallscope): a ratio-kind
    SLO over the shadow estimator's per-comparison counters — good
    fraction == online recall@k, objective == the configured recall
    target — so a recall regression burns and alerts EXACTLY like an
    availability or latency burn, flagging the window's /ask traces
    anomalous.  ``retrieve_shadow_expected`` / ``retrieve_shadow_missed``
    are stamped by ``obs/retrieval_observatory.py`` per shadow
    comparison and rolled into windows by the telemetry sampler."""
    return [
        SLODef(
            name="retrieve_recall",
            kind="ratio",
            objective=recall_target,
            total_series="retrieve_shadow_expected",
            bad_series="retrieve_shadow_missed",
            short_windows=short_windows,
            long_windows=long_windows,
            burn_threshold=burn_threshold,
            min_events=min_events,
            trace_names=("ask", "ask_stream"),
        ),
    ]


@dataclass
class _AlertState:
    firing: bool = False
    fired_at_unix: Optional[float] = None
    fired_count: int = 0
    # distinct windows seen with short burn below 1.0 while firing
    calm_windows: int = 0
    last_eval_widx: Optional[int] = None
    last_short_burn: float = 0.0
    last_long_burn: float = 0.0
    history: collections.deque = field(
        default_factory=lambda: collections.deque(maxlen=32)
    )


class BurnRateEvaluator:
    """Evaluates every SLO once per telemetry tick (the sampler calls
    :meth:`evaluate`).  Thread-safe; designed to be read (``status()``)
    from HTTP handlers while the sampler thread evaluates."""

    def __init__(
        self,
        store: TelemetryStore,
        slos: List[SLODef],
        registry=None,  # metrics registry: alert counters + gauges
        recorder=None,  # flight recorder: firing-window trace flagging
    ) -> None:
        self.store = store
        self.slos = list(slos)
        self.registry = registry
        self.recorder = recorder
        self._lock = threading.Lock()
        self._states: Dict[str, _AlertState] = {
            s.name: _AlertState() for s in self.slos
        }
        # latency objectives must pre-register their thresholds so
        # sealed windows carry over-threshold counts
        for slo in self.slos:
            if slo.kind == "latency":
                d = self._digest(slo)
                if d is not None:
                    d.register_threshold(slo.threshold_ms)

    # ---- inputs --------------------------------------------------------------

    def _digest(self, slo: SLODef):
        if self.registry is None:
            return None
        # histogram() creates on first touch — the digest (and its
        # registered threshold) must exist BEFORE the first request
        # observes into it, or early windows would lack over-counts
        h = self.registry.histogram(slo.digest_name)
        return getattr(h, "digest", None)

    def _window_burn(
        self, slo: SLODef, n_windows: int, now: Optional[float]
    ) -> Tuple[float, int]:
        """(burn rate, total events) over the last ``n_windows``."""
        if slo.kind == "latency":
            d = self._digest(slo)
            if d is None:
                return 0.0, 0
            counts = d.window_counts(
                n_windows, threshold_ms=slo.threshold_ms, now=now
            )
            total, bad = counts["total"], counts["over"]
        else:
            total = int(
                self.store.window_delta(slo.total_series, n_windows, now=now)
            )
            bad = int(
                self.store.window_delta(slo.bad_series, n_windows, now=now)
            )
        if total < slo.min_events:
            return 0.0, total
        return (bad / total) / slo.budget, total

    # ---- evaluation ----------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One pass over every SLO; returns the transitions (fired /
        cleared) this pass produced."""
        transitions: List[Dict[str, Any]] = []
        widx = self.store.widx(now)
        for slo in self.slos:
            short_burn, _ = self._window_burn(slo, slo.short_windows, now)
            long_burn, _ = self._window_burn(slo, slo.long_windows, now)
            with self._lock:
                st = self._states[slo.name]
                new_window = st.last_eval_widx != widx
                st.last_short_burn = short_burn
                st.last_long_burn = long_burn
                if not st.firing:
                    if (
                        short_burn >= slo.burn_threshold
                        and long_burn >= slo.burn_threshold
                    ):
                        st.firing = True
                        st.fired_at_unix = time.time()
                        st.fired_count += 1
                        st.calm_windows = 0
                        st.history.append(
                            {
                                "event": "fired",
                                "t_unix": st.fired_at_unix,
                                "short_burn": round(short_burn, 2),
                                "long_burn": round(long_burn, 2),
                            }
                        )
                        transitions.append(
                            {"slo": slo.name, "event": "fired"}
                        )
                        self._on_fired(slo, widx)
                else:
                    if short_burn < 1.0:
                        if new_window:
                            st.calm_windows += 1
                        if st.calm_windows >= slo.clear_windows:
                            st.firing = False
                            st.calm_windows = 0
                            st.history.append(
                                {"event": "cleared", "t_unix": time.time()}
                            )
                            transitions.append(
                                {"slo": slo.name, "event": "cleared"}
                            )
                            self._gauge(slo, 0.0)
                    else:
                        st.calm_windows = 0
                        # still burning: keep marking the current
                        # window's traces so an ongoing incident's
                        # evidence doesn't stop at the firing edge
                        self._flag_window(slo, widx, widx)
                st.last_eval_widx = widx
        return transitions

    def _on_fired(self, slo: SLODef, widx: int) -> None:
        if self.registry is not None:
            self.registry.counter(f"slo_{slo.name}_fired").inc()
        self._gauge(slo, 1.0)
        # the firing evidence: every trace in the short window that
        # crossed the threshold is flagged into the always-keep ring
        self._flag_window(slo, widx - slo.short_windows + 1, widx)

    def _gauge(self, slo: SLODef, value: float) -> None:
        if self.registry is not None:
            self.registry.gauge(f"slo_{slo.name}_burning").set(value)

    def _flag_window(self, slo: SLODef, widx_lo: int, widx_hi: int) -> None:
        if self.recorder is None:
            return
        t_lo = self.store.window_wall_start(widx_lo)
        t_hi = self.store.window_wall_start(widx_hi + 1)
        self.recorder.flag_window(
            t_lo,
            t_hi,
            f"slo_{slo.name}_burn",
            names=slo.trace_names or None,
        )

    # ---- surfaces ------------------------------------------------------------

    def status(self) -> List[Dict[str, Any]]:
        out = []
        for slo in self.slos:
            with self._lock:
                st = self._states[slo.name]
                row: Dict[str, Any] = {
                    "name": slo.name,
                    "kind": slo.kind,
                    "objective": slo.objective,
                    "burn_threshold": slo.burn_threshold,
                    "windows": [slo.short_windows, slo.long_windows],
                    "short_burn": round(st.last_short_burn, 3),
                    "long_burn": round(st.last_long_burn, 3),
                    "firing": st.firing,
                    "fired_count": st.fired_count,
                    "fired_at_unix": st.fired_at_unix,
                    "history": list(st.history),
                }
            if slo.kind == "latency":
                row["threshold_ms"] = slo.threshold_ms
                row["series"] = slo.digest_name
            else:
                row["series"] = [slo.total_series, slo.bad_series]
            out.append(row)
        return out

    def firing(self) -> List[str]:
        with self._lock:
            return [
                name for name, st in self._states.items() if st.firing
            ]

    def any_firing(self, *names: str) -> bool:
        """True if any of the named SLOs is currently burning (all SLOs
        when called with no names).  Convenience for policy hooks —
        e.g. the QoS layer's batch-deferral check — that gate on a
        subset of alerts without list plumbing."""
        with self._lock:
            if not names:
                return any(st.firing for st in self._states.values())
            return any(
                st.firing
                for name, st in self._states.items()
                if name in names
            )
