"""docqa-costscope: per-class request cost attribution.

Every observability layer so far measures *time* (traces, time-series,
dispatch/MFU) or *quality* (recallscope); nothing measures **who spends
the machine** — telemetry is aggregate, so ROADMAP item 4's
weighted-fair admission, KV preemption, and SLO-aware shedding have no
per-class accounting to act on.  This module is that accounting:

* **request class** — every request carries one of
  :data:`REQUEST_CLASSES` (``interactive`` /ask+stream, ``batch``
  summarize/synthese, ``background`` index refresh / warmup / canaries /
  shadow probes), threaded from ``service/app.py`` through qa → serve →
  pool → spine via the :class:`CostRecord` attached to the request's
  trace and to the batcher's ``_Request``;
* **cost vector** — a :class:`CostRecord` accumulates, per request:
  queue/admission wait, prefill device-ms split cold-vs-warm with
  ``prefill_tokens_avoided``, decode device-ms + tokens, retrieve
  device-ms, spine queue-wait, estimated FLOPs (the observatory's
  annotated ``cost_analysis()`` models), and **KV block-seconds** — the
  time-integral of KV blocks held, accumulated exactly by
  ``engines/paged.BlockAllocator`` with shared-block refcount awareness
  (a prefix-shared block bills each holder ``1/refcount`` per second,
  so the sum over holders equals the block's in-use time and the pool
  balances to zero residual after drain — the chaos assertion);
* **bounded aggregation** — the :class:`RequestCostLedger` folds retired
  records into per-class cumulative sums (surfaced as registry counters
  ``cost_*_<class>``, which the telemetry sampler rolls into windowed
  series on ``/api/telemetry`` and both ``/metrics`` dialects) and a
  bounded top-K table per session/prefix-key (``/api/costs`` only —
  sessions are unbounded-cardinality and must never become series);
* **shed forensics** — every ``QueueFull`` / ``BlockPoolExhausted`` /
  ``SpineSaturated`` / deadline shed calls :meth:`record_shed`, which
  captures a *pressure snapshot* (which classes held how many KV
  blocks, decode lanes, and queue slots at that instant — the probe the
  runtime wires over the batcher/pool/spine) into a bounded ring served
  by ``GET /api/costs/sheds``: an interactive shed caused by batch load
  is visible, not inferred.

Exactly-once: a record retires once (first caller wins — the batcher's
``_finish``, a pool-level shed, or the trace-completion fallback in
``obs/recorder.py``); later cost deltas (e.g. KV block-seconds billed
by a teardown sweep that runs after the typed failure) still fold into
the aggregates via late-add, so accounting stays exact under
eviction/failover without ever double-counting a request.

Stdlib-only like the rest of ``docqa_tpu/obs`` (the metrics registry is
resolved lazily); every surface is fenced — cost accounting must never
fail a request.

PHI policy: class names, session *hashes* (the prefix key is already a
``(template hash, chunk-set hash)`` pair), counts, and durations only —
never query or document text.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

REQUEST_CLASSES = ("interactive", "batch", "background")

# the one fallback bucket: anything outside the taxonomy aggregates
# here, so series cardinality is bounded by construction
OTHER_CLASS = "other"

# fields a CostRecord accumulates (floats; ms unless named otherwise)
COST_FIELDS = (
    "queue_wait_ms",          # serve queue: submit -> admission pop
    "spine_queue_wait_ms",    # attributed dispatch-spine queue wait
    "prefill_device_ms_cold",
    "prefill_device_ms_warm",
    "prefill_tokens",
    "prefill_tokens_avoided",  # prefix-cache shared tokens (docqa-prefix)
    "decode_device_ms",
    "decode_tokens",
    "retrieve_device_ms",
    "other_device_ms",        # traced spine items outside the buckets
    "flops_est",              # observatory cost-model attribution
    "kv_block_seconds",       # paged-KV time integral (engines/paged.py)
    # block-seconds a QoS preemption threw away (docqa-qos): the
    # victim's holding up to eviction, ALSO billed under
    # kv_block_seconds (the identity stays exact) — this line names
    # the waste so operators can price the policy
    "preempted_block_seconds",
)

# fields whose per-class cumulative sums ride the metrics registry as
# counters (bounded: len(classes) x len(this)); the rest stay
# /api/costs-only detail
_COUNTER_FIELDS = (
    "queue_wait_ms",
    "prefill_device_ms_cold",
    "prefill_device_ms_warm",
    "prefill_tokens_avoided",
    "decode_device_ms",
    "decode_tokens",
    "retrieve_device_ms",
    "kv_block_seconds",
    "flops_est",
    "preempted_block_seconds",  # mints cost_preempted_block_seconds_<cls>
)

_DEVICE_FIELDS = (
    "prefill_device_ms_cold",
    "prefill_device_ms_warm",
    "decode_device_ms",
    "retrieve_device_ms",
    "other_device_ms",
)

SHED_OUTCOMES = frozenset(
    {
        "shed_deadline", "shed_queue", "shed_block_pool", "shed_spine",
        # QoS batch deferral (serve.DeferredByPolicy, docqa-qos): a
        # policy choice, not a capacity shed — kept distinguishable so
        # "how much batch did self-protection turn away" is a ledger
        # query, not a log grep
        "shed_deferred",
    }
)


def normalize_class(cls: Optional[str]) -> str:
    return cls if cls in REQUEST_CLASSES else OTHER_CLASS


_REGISTRY_CACHE: Any = None


def _default_registry():
    """Lazy metrics-registry resolution (keeps this module's import
    stdlib-only, the obs discipline)."""
    global _REGISTRY_CACHE
    if _REGISTRY_CACHE is None:
        try:
            from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY

            _REGISTRY_CACHE = DEFAULT_REGISTRY
        except Exception:  # pragma: no cover - import cycle safety net
            _REGISTRY_CACHE = False
    return _REGISTRY_CACHE or None


class CostRecord:
    """One request's cost vector.  Thread-safe: the batcher worker, the
    spine accounting hook, and waiter threads all add to it; adds after
    retirement forward to the ledger's aggregates (late-add) so a
    teardown sweep billing KV block-seconds after a typed failure still
    lands exactly once."""

    __slots__ = (
        "cls", "session", "trace", "t_open", "outcome", "f",
        "_lock", "_retired", "_ledger",
    )

    def __init__(
        self,
        ledger: "RequestCostLedger",
        cls: str,
        session: Optional[str] = None,
        trace: Any = None,
    ) -> None:
        self._ledger = ledger
        self.cls = normalize_class(cls)
        self.session = session
        self.trace = trace
        self.t_open = time.monotonic()
        self.outcome: Optional[str] = None
        self.f: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._retired = False

    # ---- accumulation --------------------------------------------------------

    def add(self, field: str, value: float) -> None:
        if not value:
            return
        with self._lock:
            if self._retired:
                late = True
            else:
                late = False
                self.f[field] = self.f.get(field, 0.0) + float(value)
        if late:
            self._ledger._fold(
                self.cls, self.session, {field: float(value)}
            )

    def set_session(self, session: Optional[str]) -> None:
        if session and self.session is None:
            self.session = session

    def account_dispatch(
        self, stage: str, queue_wait_s: float, device_s: float
    ) -> None:
        """Spine hook (engines/spine.py): a work item submitted UNDER
        this request's trace completed.  Worker-side serve items carry
        no trace and are attributed explicitly by the batcher — so this
        path covers the submitter-side stages (retrieval, store search,
        solo generate) with no double count."""
        self.add("spine_queue_wait_ms", queue_wait_s * 1e3)
        if stage.startswith(("retrieve", "store_search", "fused")):
            self.add("retrieve_device_ms", device_s * 1e3)
        else:
            self.add("other_device_ms", device_s * 1e3)

    def _finalize(self, outcome: str) -> Optional[Dict[str, float]]:
        """Retirement CAS: first caller wins and gets the field
        snapshot to fold; every later caller gets None.  The one place
        ``_retired`` flips — the ledger never touches this record's
        guarded state directly."""
        with self._lock:
            if self._retired:
                return None
            self._retired = True
            self.outcome = outcome
            return dict(self.f)

    # ---- views ---------------------------------------------------------------

    @property
    def retired(self) -> bool:
        with self._lock:
            return self._retired

    def device_ms_total(self) -> float:
        with self._lock:
            return sum(self.f.get(k, 0.0) for k in _DEVICE_FIELDS)

    def snapshot_fields(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.f)

    def summary(self) -> Dict[str, Any]:
        """Compact cost summary (attached to the trace at retirement —
        exported on the timeline and the Chrome trace)."""
        with self._lock:
            f = dict(self.f)
            outcome = self.outcome
        out: Dict[str, Any] = {
            "class": self.cls,
            "outcome": outcome,
            "device_ms": round(
                sum(f.get(k, 0.0) for k in _DEVICE_FIELDS), 3
            ),
        }
        if self.session:
            out["session"] = self.session
        for k, v in sorted(f.items()):
            out[k] = round(v, 3)
        return out


class RequestCostLedger:
    """Bounded per-class (and top-K per-session) cost aggregation plus
    the shed-forensics ring.  One per process (:data:`DEFAULT_COST_
    LEDGER`); ``service/app.py`` wires the pressure probe and serves
    :meth:`snapshot` on ``GET /api/costs``."""

    def __init__(
        self,
        registry: Any = None,
        max_sessions: int = 64,
        shed_ring: int = 64,
    ) -> None:
        self._registry = registry
        self.max_sessions = int(max_sessions)
        self._lock = threading.Lock()
        self._enabled = True
        # cls -> {field: cumulative, "requests": n, outcomes...}
        self._classes: Dict[str, Dict[str, float]] = {}
        self._outcomes: Dict[str, Dict[str, int]] = {}
        # session -> {"cls", "requests", "device_ms", "kv_block_seconds"}
        self._sessions: Dict[str, Dict[str, Any]] = {}
        self._sheds: collections.deque = collections.deque(
            maxlen=max(1, int(shed_ring))
        )
        self._shed_counts: Dict[str, int] = {}
        self._retired_total = 0
        self._pressure_probe: Optional[Callable[[], Dict[str, Any]]] = None

    # ---- wiring --------------------------------------------------------------

    def set_enabled(self, value: bool) -> None:
        """The cost-overhead A/B's switch: disabled, :meth:`open`
        returns None and every call site's ``is not None`` guard makes
        accounting cost one attribute read."""
        self._enabled = bool(value)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_pressure_probe(
        self, probe: Optional[Callable[[], Dict[str, Any]]]
    ) -> None:
        """Register the closure :meth:`record_shed` snapshots — the
        runtime wires one over the batcher/pool + spine.  Must be cheap
        and lock-light: it runs on the shedding thread."""
        self._pressure_probe = probe

    def registry(self):
        return self._registry if self._registry is not None else (
            _default_registry()
        )

    # ---- record lifecycle ----------------------------------------------------

    def open(
        self,
        cls: str,
        session: Optional[str] = None,
        trace: Any = None,
    ) -> Optional[CostRecord]:
        """Mint a record (None when the ledger is disabled).  When a
        ``trace`` is given the record is attached as
        ``trace.cost_record`` — the spine's accounting hook and the
        batcher's ``make_request`` both find it there, which is how one
        HTTP request's retrieval, prefill, decode, and KV holdings land
        on ONE record."""
        if not self._enabled:
            return None
        rec = CostRecord(self, cls, session=session, trace=trace)
        if trace is not None:
            trace.cost_record = rec
        return rec

    def retire(self, rec: Optional[CostRecord], outcome: str = "ok") -> bool:
        """Fold a record into the aggregates — exactly once (the first
        caller wins; False = already retired).  ``outcome`` is ``ok``, a
        ``shed_*`` kind, ``cancelled``, ``failed_replica``, or
        ``error``."""
        if rec is None:
            return False
        fields = rec._finalize(outcome)
        if fields is None:
            return False
        self._fold(rec.cls, rec.session, fields, outcome=outcome)
        if rec.trace is not None:
            try:
                rec.trace.cost_summary = rec.summary()
            except Exception:  # a finished/foreign trace must never fail this
                pass
        return True

    def _fold(
        self,
        cls: str,
        session: Optional[str],
        fields: Dict[str, float],
        outcome: Optional[str] = None,
    ) -> None:
        dev_ms = sum(fields.get(k, 0.0) for k in _DEVICE_FIELDS)
        with self._lock:
            row = self._classes.setdefault(cls, {})
            for k, v in fields.items():
                row[k] = row.get(k, 0.0) + v
            row["device_ms"] = row.get("device_ms", 0.0) + dev_ms
            if outcome is not None:
                row["requests"] = row.get("requests", 0.0) + 1
                oc = self._outcomes.setdefault(cls, {})
                oc[outcome] = oc.get(outcome, 0) + 1
                self._retired_total += 1
            if session:
                srow = self._sessions.get(session)
                if srow is None:
                    if len(self._sessions) >= self.max_sessions:
                        # bounded: evict the smallest spender (a table of
                        # top-K by construction, never a cardinality leak)
                        victim = min(
                            self._sessions,
                            key=lambda s: self._sessions[s]["device_ms"],
                        )
                        del self._sessions[victim]
                    srow = self._sessions[session] = {
                        "cls": cls, "requests": 0, "device_ms": 0.0,
                        "kv_block_seconds": 0.0,
                    }
                if outcome is not None:
                    srow["requests"] += 1
                srow["device_ms"] += dev_ms
                srow["kv_block_seconds"] += fields.get(
                    "kv_block_seconds", 0.0
                )
        reg = self.registry()
        if reg is not None:
            try:
                if outcome is not None:
                    # shed counting lives in record_shed (one bump per
                    # shed EVENT incl. spine saturation, which never
                    # retires through a typed serve outcome) — bumping
                    # here too would double-count every typed shed
                    reg.counter(f"cost_requests_{cls}").inc()
                if dev_ms:
                    reg.counter(f"cost_device_ms_{cls}").inc(dev_ms)
                for k in _COUNTER_FIELDS:
                    v = fields.get(k, 0.0)
                    if v:
                        reg.counter(f"cost_{k}_{cls}").inc(v)
            except Exception:  # metrics must never fail accounting
                pass

    # ---- shed forensics ------------------------------------------------------

    def record_shed(
        self, kind: str, cls: Optional[str] = None, **attrs: Any
    ) -> Optional[Dict[str, Any]]:
        """Capture one shed's pressure snapshot into the bounded ring
        (``/api/costs/sheds``): the shed kind, the shed REQUEST's class,
        and — via the registered probe — which classes held how many KV
        blocks, decode lanes, and queue slots at that instant.  Fenced
        and cheap; returns the snapshot (tests/bench read it back)."""
        if not self._enabled:
            return None
        snap: Dict[str, Any] = {
            "t_unix": time.time(),
            "kind": kind,
            "class": normalize_class(cls) if cls is not None else None,
        }
        if attrs:
            snap.update(attrs)
        probe = self._pressure_probe
        if probe is not None:
            try:
                pressure = probe() or {}
                snap["pressure"] = pressure
                by_class = pressure.get("by_class") or {}
                if by_class:
                    majority = max(
                        by_class,
                        key=lambda c: by_class[c].get("kv_blocks", 0),
                    )
                    if by_class[majority].get("kv_blocks", 0) > 0:
                        snap["majority_block_class"] = majority
            except Exception:
                snap["pressure_error"] = True
        with self._lock:
            self._sheds.append(snap)
            self._shed_counts[kind] = self._shed_counts.get(kind, 0) + 1
        reg = self.registry()
        if reg is not None:
            try:
                reg.counter("cost_shed_snapshots").inc()
                if cls is not None:
                    # per-class shed series (the runbook's trend input):
                    # one bump per shed EVENT, the single count source
                    reg.counter(
                        f"cost_sheds_{normalize_class(cls)}"
                    ).inc()
            except Exception:
                pass
        return snap

    def sheds(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-last ring contents; ``n`` bounds to the most recent n
        (None = all, <= 0 = none — never the slicing surprise where
        ``[-0:]`` would return everything)."""
        with self._lock:
            out = list(self._sheds)
        if n is None:
            return out
        return out[-n:] if n > 0 else []

    # ---- surfaces ------------------------------------------------------------

    def class_totals(self) -> Dict[str, Dict[str, float]]:
        """Deep-copied per-class cumulative sums (bench A/B windows
        difference two of these)."""
        with self._lock:
            return {c: dict(row) for c, row in self._classes.items()}

    def top_sessions(self, k: int = 10) -> List[Dict[str, Any]]:
        with self._lock:
            rows = [
                {"session": s, **row} for s, row in self._sessions.items()
            ]
        rows.sort(key=lambda r: -r["device_ms"])
        for r in rows:
            r["device_ms"] = round(r["device_ms"], 3)
            r["kv_block_seconds"] = round(r["kv_block_seconds"], 6)
        return rows[:k]

    def snapshot(
        self,
        spine_device_s: Optional[float] = None,
        pool_block_seconds: Optional[float] = None,
    ) -> Dict[str, Any]:
        """The ``GET /api/costs`` payload: per-class breakdown, top
        spenders, and each class's share of measured device time
        (vs the spine's total — the cross-check the bench asserts) and
        of the KV pool's block-seconds."""
        with self._lock:
            classes = {c: dict(row) for c, row in self._classes.items()}
            outcomes = {c: dict(o) for c, o in self._outcomes.items()}
            shed_counts = dict(self._shed_counts)
            n_sheds = len(self._sheds)
            retired = self._retired_total
        total_dev_ms = sum(r.get("device_ms", 0.0) for r in classes.values())
        total_kv = sum(
            r.get("kv_block_seconds", 0.0) for r in classes.values()
        )
        out_classes: Dict[str, Any] = {}
        for c, row in sorted(classes.items()):
            entry = {k: round(v, 3) for k, v in sorted(row.items())}
            entry["outcomes"] = outcomes.get(c, {})
            dev = row.get("device_ms", 0.0)
            entry["share_of_attributed_device"] = (
                round(dev / total_dev_ms, 4) if total_dev_ms else None
            )
            if spine_device_s:
                entry["share_of_spine_device"] = round(
                    (dev / 1e3) / spine_device_s, 4
                )
            kv = row.get("kv_block_seconds", 0.0)
            entry["share_of_kv_block_seconds"] = (
                round(kv / total_kv, 4) if total_kv else None
            )
            if pool_block_seconds:
                entry["share_of_kv_pool"] = round(
                    kv / pool_block_seconds, 4
                )
            out_classes[c] = entry
        return {
            "enabled": self._enabled,
            "classes": out_classes,
            "requests_retired": retired,
            "attributed_device_ms": round(total_dev_ms, 3),
            "spine_device_ms": (
                round(spine_device_s * 1e3, 3)
                if spine_device_s is not None
                else None
            ),
            "attributed_device_coverage": (
                round((total_dev_ms / 1e3) / spine_device_s, 4)
                if spine_device_s
                else None
            ),
            "kv_block_seconds_total": round(total_kv, 6),
            "pool_block_seconds": (
                round(pool_block_seconds, 6)
                if pool_block_seconds is not None
                else None
            ),
            "top_sessions": self.top_sessions(),
            "sheds": {"recorded": n_sheds, "by_kind": shed_counts},
        }

    def telemetry_gauges(self) -> Dict[str, float]:
        """Bounded live gauges for the telemetry sampler's extra-probe
        hook (the per-class counters ride the registry scrape)."""
        with self._lock:
            n_sessions = len(self._sessions)
            top = max(
                (r["device_ms"] for r in self._sessions.values()),
                default=0.0,
            )
            n_sheds = len(self._sheds)
        return {
            "cost_sessions_tracked": float(n_sessions),
            "cost_top_session_device_ms": round(top, 3),
            "cost_shed_ring_depth": float(n_sheds),
        }

    def reset(self) -> None:
        """Zero the aggregates (bench measurement windows).  Open
        records keep working — their retire/late-adds fold into the
        fresh sums."""
        with self._lock:
            self._classes.clear()
            self._outcomes.clear()
            self._sessions.clear()
            self._sheds.clear()
            self._shed_counts.clear()
            self._retired_total = 0


DEFAULT_COST_LEDGER = RequestCostLedger()


def cost_record_of(trace: Any) -> Optional[CostRecord]:
    """The record attached to a trace, if any (duck-typed: traces are
    plain objects; absent attribute = unattributed)."""
    if trace is None:
        return None
    return getattr(trace, "cost_record", None)


def cost_open(ctx: Any, cls: str) -> Optional[CostRecord]:
    """Endpoint idiom (service/app.py): attach a class-stamped record to
    a just-opened trace context.  No-ops (None) when tracing is off or
    the ledger is disabled; reuses an already-attached record rather
    than double-opening."""
    if ctx is None:
        return None
    existing = cost_record_of(ctx.trace)
    if existing is not None:
        return existing
    return DEFAULT_COST_LEDGER.open(cls, trace=ctx.trace)
