"""docqa-recallscope: online retrieval-quality estimation for the tiered index.

Every observability layer so far measures *time* (traces, time-series,
dispatch/MFU); nothing measures *retrieval quality* — yet the IVF tier
trades recall for latency on a knob (``nprobe``) nobody can see the
frontier of, and ROADMAP item 2 is blocked on "tune nprobe against a
measured recall target".  This module is the measurement substrate:

* **shadow sampling** — a configurable fraction of live tiered
  retrievals (default 1/32, deterministic seeded sampler so replayed
  workloads sample identically across restarts) gets an asynchronous
  exact-scan shadow query: the ground truth the tier approximates,
  dispatched on the spine's *background* stream (capped at n_lanes-1,
  never blocking a serving lane) under its own ``retrieve_shadow``
  stage so ``dispatch_*`` telemetry attributes its cost;
* **online recall@k** — shadow top-k vs served top-k (tie-tolerant:
  a served row scoring at least the shadow's k-th score counts — two
  equal-scored rows are interchangeable evidence) folded into windowed
  estimates with Wilson confidence intervals, per (tier, nprobe);
* **drift digests** — served score margins and raw query norms feed
  registry histograms (``retrieve_score_margin`` / ``retrieve_query_
  norm``): an embedding-distribution shift moves these before recall
  visibly degrades;
* **the measured nprobe frontier** — every Nth sampled shadow also
  re-probes the IVF tier at neighboring nprobe values, yielding an
  *observed* recall/latency curve and a recommended nprobe for the
  configured recall target.  Recommendation only by default;
  ``auto_apply`` (config ``retrieval_quality.auto_apply_nprobe``,
  default OFF) lets the observatory apply it live via a callback the
  runtime wires to ``TieredIndex.set_nprobe``;
* **the recall SLO** — per-comparison expected/missed counts ride
  registry counters (``retrieve_shadow_expected`` / ``retrieve_shadow_
  missed``) that the telemetry sampler rolls into windows, so
  ``obs/slo.py:default_retrieval_slos`` evaluates a ratio-kind burn
  exactly like availability: a recall regression fires an alert and
  flags the window's /ask traces anomalous.

Stdlib-only like the rest of ``docqa_tpu/obs`` — jax is never imported
here.  The device work lives in closures built by the call sites
(``index/tiered.py``, ``engines/retrieve.py``) over their own
snapshotted state; the observatory only runs them on its worker thread,
where each internal dispatch rides the spine like any other submitter's.

PHI policy: everything the observatory *stores, exports, or logs* —
comparison windows, frontier evidence, counters, ``/api/retrieval`` —
carries row ids, scores, latencies, and norms only, never query or
document text.  That now includes the pending queue itself: a queued
:class:`ShadowJob` holds query EMBEDDINGS (the served dispatch returns
them, so the shadow never re-encodes) plus a salted content hash for
dedup/labels — no raw query text is reachable from a queued job, so a
diagnostic that serialized the queue could not leak one (the fused
path's former raw-text closure is gone; regression-tested in
``tests/test_retrieval_obs.py``).
"""

from __future__ import annotations

import collections
import logging
import math
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("docqa.recallscope")

# same deterministic multiplicative hash the telemetry digests use for
# their sample slots: no RNG, so a replayed workload shadows the exact
# same request indices across restarts
_HASH_MULT = 2654435761
_SEED_MULT = 40503
_TIE_EPS = 1e-6


def wilson_interval(
    hits: int, total: int, z: float = 1.96
) -> Tuple[float, float]:
    """Wilson score interval for a binomial proportion — the small-n
    honest alternative to the normal approximation (which collapses to a
    zero-width interval at recall 1.0 and escapes [0, 1] near the
    edges).  Returns ``(lo, hi)``; ``(0.0, 1.0)`` when ``total == 0``
    (no evidence constrains nothing)."""
    if total <= 0:
        return 0.0, 1.0
    p = hits / total
    z2 = z * z
    denom = 1.0 + z2 / total
    center = (p + z2 / (2.0 * total)) / denom
    spread = (
        z
        * math.sqrt(p * (1.0 - p) / total + z2 / (4.0 * total * total))
        / denom
    )
    lo = max(0.0, center - spread)
    hi = min(1.0, center + spread)
    # the degenerate edges are EXACT mathematically (center ± spread
    # telescopes to the boundary at p ∈ {0, 1}); pin them so float
    # round-off can't report hi=0.99999... for a perfect window
    if hits >= total:
        hi = 1.0
    if hits <= 0:
        lo = 0.0
    return lo, hi


def compare_topk(
    served: Sequence[Tuple[int, float]],
    shadow: Sequence[Tuple[int, float]],
    k: int,
) -> Tuple[int, int]:
    """(hits, expected) for one query's served vs exact-shadow top-k.

    ``expected`` is what the exact scan actually found (min(k,
    len(shadow)) — a corpus with 2 live rows owes nobody 10).  A served
    row is a hit when its id is in the shadow set, OR when its score
    reaches the shadow's k-th (minimum) score within a tie epsilon:
    under duplicate scores exact top-k picks an arbitrary
    representative, and a served row of equal score is equally correct
    evidence, not a recall miss."""
    expected = min(k, len(shadow))
    if expected == 0:
        return 0, 0
    shadow_ids = {int(rid) for rid, _ in shadow[:expected]}
    kth = min(float(s) for _, s in shadow[:expected])
    hits = 0
    for rid, score in served[:expected]:
        if int(rid) in shadow_ids or float(score) >= kth - _TIE_EPS:
            hits += 1
    return min(hits, expected), expected


class _EstimateWindow:
    """Bounded window of PER-QUERY (hits, expected) comparison pairs;
    the estimate is hits/expected over the retained window with a
    Wilson CI.  One pair per query, not per shadow job — otherwise
    ``comparisons`` (and every ``min_frontier_n``-style evidence floor
    read against it) would mean 20x different evidence at batch 20 than
    at batch 1."""

    def __init__(self, window: int = 512) -> None:
        self._pairs: collections.deque = collections.deque(maxlen=window)

    def add(self, hits: int, expected: int) -> None:
        if expected > 0:
            self._pairs.append((int(hits), int(expected)))

    def estimate(self) -> Optional[Dict[str, Any]]:
        if not self._pairs:
            return None
        hits = sum(h for h, _ in self._pairs)
        total = sum(e for _, e in self._pairs)
        lo, hi = wilson_interval(hits, total)
        return {
            "recall": round(hits / total, 4) if total else None,
            "ci_lo": round(lo, 4),
            "ci_hi": round(hi, 4),
            "hits": hits,
            "expected": total,
            "comparisons": len(self._pairs),
        }


@dataclass
class ShadowJob:
    """One sampled retrieval, queued for the worker thread.

    ``served``: per query a list of (row_id, score).  ``shadow_fn``
    returns ``(shadow_rows, queries_or_None)`` — the exact ground truth
    plus (when cheaply available) the query embeddings the frontier
    probes reuse.  ``frontier_fn(queries, nprobe)`` returns
    ``(rows, seconds)`` for one neighbor probe.  Both closures run ONLY
    on the observatory worker; every device dispatch inside them rides
    the spine's background ``probe`` stream under the
    ``retrieve_shadow`` stage."""

    tier: str
    nprobe: int
    k: int
    served: List[List[Tuple[int, float]]]
    shadow_fn: Callable[[], Tuple[List[List[Tuple[int, float]]], Any]]
    frontier_fn: Optional[Callable[[Any, int], Tuple[list, float]]] = None
    covered: Optional[int] = None
    n_clusters: Optional[int] = None
    query_norms: Optional[List[float]] = None
    served_margins: Optional[List[float]] = None
    seq: int = 0
    attrs: Dict[str, Any] = field(default_factory=dict)


class RetrievalObservatory:
    """Shadow-sampling online recall estimator + nprobe-frontier plane.

    Thread model: serving threads call :meth:`sample` (a counter bump +
    one deterministic hash) and, on a hit, :meth:`submit` (a bounded
    enqueue); ONE worker thread drains jobs and does all comparison /
    estimation / frontier work, so the serving path never waits on a
    shadow.  All mutable state is guarded by ``_lock``; the worker is
    joined in :meth:`stop` (thread-lifecycle rule).
    """

    def __init__(
        self,
        sample_every: int = 32,
        seed: int = 0,
        window: int = 512,
        max_pending: int = 8,
        frontier_every: int = 4,
        frontier_factors: Sequence[float] = (0.25, 0.5, 1.0, 2.0, 4.0),
        min_frontier_n: int = 5,
        recall_target: float = 0.95,
        auto_apply: bool = False,
        apply_nprobe: Optional[Callable[[int], Any]] = None,
        registry=None,  # runtime.metrics.MetricsRegistry (duck-typed)
    ) -> None:
        self.sample_every = max(1, int(sample_every))
        self.seed = int(seed)
        self.window = int(window)
        self.max_pending = max(1, int(max_pending))
        # every Nth sampled shadow also probes the frontier; 0 disables
        # frontier probing entirely (bench overhead arms)
        self.frontier_every = max(0, int(frontier_every))
        self.frontier_factors = tuple(frontier_factors)
        self.min_frontier_n = int(min_frontier_n)
        self.recall_target = float(recall_target)
        self.auto_apply = bool(auto_apply)
        self.apply_nprobe = apply_nprobe
        self.registry = registry
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        self._seq = 0  # retrieval sequence number (sampler input)
        self._n_sampled = 0
        self._n_dropped = 0
        self._n_errors = 0
        self._n_shadows = 0
        # (tier, nprobe) -> _EstimateWindow; _current_key tracks the
        # serving configuration the gauge surface reports
        self._windows: Dict[Tuple[str, int], _EstimateWindow] = {}
        self._current_key: Optional[Tuple[str, int]] = None
        # nprobe -> {"window": _EstimateWindow, "lat_ms": deque,
        #            "compiled": bool}; _frontier_sig is the tier-build
        # signature (n_clusters, covered) the evidence was measured
        # against — a rebuild reclusters, which changes what any given
        # nprobe MEANS, so stale windows must not feed the
        # recommendation (let alone auto-apply)
        self._frontier: Dict[int, Dict[str, Any]] = {}
        self._frontier_sig: Optional[Tuple[Any, Any]] = None
        self._applied_nprobe: Optional[int] = None
        self._busy = False  # worker mid-_process (drain() observability)
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle -----------------------------------------------------------

    def start(self) -> "RetrievalObservatory":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="recallscope"
        )
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        """Idempotent; joins the worker.  Shadow closures only run
        bounded device probes, so the join bound is slack."""
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=join_timeout)
            if t.is_alive():
                log.warning("recallscope worker still alive after stop()")
            else:
                self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ---- sampling (serving-thread surface) -----------------------------------

    def _sampled(self, seq: int) -> bool:
        """Deterministic per-sequence decision, exact 1-in-N for ANY
        rate: every window of ``sample_every`` consecutive retrievals
        samples exactly one, at a slot chosen by a pure hash of (seed,
        window index).  A restarted process replaying the same workload
        shadows the same request indices — no RNG state to diverge —
        and the hashed slot keeps the cadence from phase-locking onto a
        periodic workload the way a bare ``seq % N == 0`` would.  (A
        residue of the raw hash is only window-exact for power-of-two
        rates; the per-window slot holds the bench A/B's '2x the rate
        contains real shadows' sizing for every operator-tuned N.)"""
        win, offset = divmod(seq, self.sample_every)
        h = ((win + 1) * _HASH_MULT + self.seed * _SEED_MULT) & 0xFFFFFFFF
        return offset == h % self.sample_every

    def sample(self) -> bool:
        """Called once per tiered retrieval.  Counts it, returns whether
        this one is shadow-sampled; the caller only builds a job on
        True.  Never samples while the worker is not running (disabled
        observability must cost zero shadow dispatches)."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        self._count("retrieve_served_total")
        if not self.running:
            return False
        return self._sampled(seq)

    def submit(self, job: ShadowJob) -> bool:
        """Bounded enqueue (serving thread).  Returns False (and counts
        the drop) when the worker is behind — shadow evidence is
        sampled anyway, so dropping beats unbounded queueing."""
        with self._lock:
            job.seq = self._n_sampled
            self._n_sampled += 1
            if len(self._pending) >= self.max_pending:
                self._n_dropped += 1
                dropped = True
            else:
                self._pending.append(job)
                dropped = False
        if dropped:
            self._count("retrieve_shadow_dropped")
            return False
        self._wake.set()
        return True

    # ---- worker --------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                job = self._pending.popleft() if self._pending else None
                self._busy = job is not None
            if job is None:
                # idle: wait for a submit (or stop); 0.2s re-check keeps
                # shutdown prompt even if a wake is lost
                self._wake.wait(0.2)
                self._wake.clear()
                continue
            try:
                self._process(job)
            except Exception:
                # a failing shadow must never kill the worker — the
                # whole point is observing the index while it misbehaves
                with self._lock:
                    self._n_errors += 1
                self._count("retrieve_shadow_errors")
                log.exception("shadow job failed (tier=%s)", job.tier)
            finally:
                with self._lock:
                    self._busy = False

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until every queued job has been processed AND the
        worker is idle (tests, the bench's A/B windows).  True on
        success; False when the timeout expired first."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            with self._lock:
                idle = not self._pending and not self._busy
            if idle:
                return True
            self._wake.set()
            _time.sleep(0.02)
        return False

    def _process(self, job: ShadowJob) -> None:
        shadow_rows, queries = job.shadow_fn()
        key = (job.tier, int(job.nprobe))
        hits_total = expected_total = 0
        recalls: List[float] = []
        pairs: List[Tuple[int, int]] = []
        for qi, served_row in enumerate(job.served):
            shadow_row = shadow_rows[qi] if qi < len(shadow_rows) else []
            hits, expected = compare_topk(served_row, shadow_row, job.k)
            hits_total += hits
            expected_total += expected
            if expected:
                recalls.append(hits / expected)
                pairs.append((hits, expected))
        with self._lock:
            self._n_shadows += 1
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = _EstimateWindow(self.window)
            for h, e in pairs:
                win.add(h, e)
            self._current_key = key
        self._count("retrieve_shadow_total")
        self._count("retrieve_shadow_expected", expected_total)
        self._count(
            "retrieve_shadow_missed", expected_total - hits_total
        )
        reg = self.registry
        if reg is not None:
            for r in recalls:
                reg.histogram("retrieve_recall").observe(r)
            for m in job.served_margins or ():
                reg.histogram("retrieve_score_margin").observe(float(m))
            for n in job.query_norms or ():
                reg.histogram("retrieve_query_norm").observe(float(n))
        if (
            job.frontier_fn is not None
            and queries is not None
            and self.frontier_every > 0
            and job.seq % self.frontier_every == 0
        ):
            self._probe_frontier(job, shadow_rows, queries)

    # ---- frontier ------------------------------------------------------------

    def frontier_candidates(
        self, nprobe: int, n_clusters: Optional[int]
    ) -> List[int]:
        cap = int(n_clusters) if n_clusters else max(1, nprobe)
        out = sorted(
            {
                min(cap, max(1, int(round(nprobe * f))))
                for f in self.frontier_factors
            }
        )
        return out

    def _probe_frontier(self, job: ShadowJob, shadow_rows, queries) -> None:
        """Re-probe the bulk tier at neighboring nprobe values against
        the shadow's *bulk* ground truth (ids below the tier watermark:
        the tail is exact at every nprobe, so only bulk recall moves
        with the knob)."""
        covered = job.covered
        # (n_clusters, covered) only changes when the tier is rebuilt:
        # both are fixed at build time (the tail grows, the watermark
        # doesn't).  Evidence measured against the old clustering says
        # nothing about recall at any nprobe under the new one.
        sig = (job.n_clusters, job.covered)
        with self._lock:
            if self._frontier_sig != sig:
                if self._frontier:
                    log.info(
                        "recallscope: tier rebuilt (%s -> %s); frontier "
                        "evidence reset", self._frontier_sig, sig,
                    )
                self._frontier.clear()
                self._frontier_sig = sig
        bulk_truth: List[List[Tuple[int, float]]] = []
        for row in shadow_rows:
            if covered is None:
                bulk_truth.append(list(row))
            else:
                bulk_truth.append(
                    [(rid, s) for rid, s in row if int(rid) < covered]
                )
        for p in self.frontier_candidates(job.nprobe, job.n_clusters):
            try:
                res = job.frontier_fn(queries, p)
            except Exception:
                self._count("retrieve_shadow_errors")
                log.exception("frontier probe failed at nprobe=%d", p)
                continue
            # IVFIndex.timed_probe reports per-shape compile freshness
            # as a third element; plain (rows, seconds) closures fall
            # back to the first-sample-per-nprobe drop below
            if len(res) == 3:
                rows, seconds, fresh = res
            else:
                rows, seconds = res
                fresh = None
            probe_pairs: List[Tuple[int, int]] = []
            for qi, truth in enumerate(bulk_truth):
                served = rows[qi] if qi < len(rows) else []
                h, e = compare_topk(served, truth, job.k)
                if e:
                    probe_pairs.append((h, e))
            with self._lock:
                entry = self._frontier.get(p)
                if entry is None:
                    entry = self._frontier[p] = {
                        "window": _EstimateWindow(self.window),
                        "lat_ms": collections.deque(maxlen=64),
                        "compiled": False,
                    }
                for h, e in probe_pairs:
                    entry["window"].add(h, e)
                if fresh is not None:
                    # authoritative: the probe itself says whether this
                    # sample paid a trace+compile (keyed per shape, so a
                    # new batch size at an old nprobe is still excluded)
                    if not fresh:
                        entry["lat_ms"].append(seconds * 1e3)
                elif entry["compiled"]:
                    entry["lat_ms"].append(seconds * 1e3)
                else:
                    # the first probe at a new nprobe traces+compiles on
                    # the lane — recording it would poison the latency
                    # axis with a one-time cost
                    entry["compiled"] = True
        self._maybe_auto_apply(job.nprobe)

    def recommended_nprobe(self) -> Optional[int]:
        """Smallest frontier nprobe whose measured recall estimate meets
        the target over at least ``min_frontier_n`` comparisons; None
        until the frontier has enough evidence."""
        with self._lock:
            rows = [
                (p, e["window"].estimate())
                for p, e in sorted(self._frontier.items())
            ]
        qualified = [
            p
            for p, est in rows
            if est is not None
            and est["comparisons"] >= self.min_frontier_n
            and est["recall"] is not None
            and est["recall"] >= self.recall_target
        ]
        return min(qualified) if qualified else None

    def _maybe_auto_apply(self, current_nprobe: int) -> None:
        if not self.auto_apply or self.apply_nprobe is None:
            return
        rec = self.recommended_nprobe()
        with self._lock:
            already = self._applied_nprobe
        if rec is None or rec == current_nprobe or rec == already:
            return
        try:
            self.apply_nprobe(rec)
        except Exception:
            log.exception("auto-apply of nprobe=%d failed", rec)
            return
        with self._lock:
            self._applied_nprobe = rec
        self._count("retrieve_nprobe_autoapplied")
        log.warning(
            "recallscope auto-applied nprobe %d -> %d (measured frontier "
            "meets recall target %.3f)",
            current_nprobe, rec, self.recall_target,
        )

    # ---- surfaces ------------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        if self.registry is not None and n:
            self.registry.counter(name).inc(n)

    def _estimates_locked(self) -> Dict[str, Any]:
        out = {}
        for (tier, nprobe), win in sorted(self._windows.items()):
            est = win.estimate()
            if est is not None:
                out[f"{tier}@nprobe={nprobe}"] = est
        return out

    def status(self) -> Dict[str, Any]:
        """The ``/api/retrieval`` payload: live estimates, drift
        digests, the observed frontier, and the recommendation."""
        with self._lock:
            current = self._current_key
            cur_est = (
                self._windows[current].estimate() if current else None
            )
            estimates = self._estimates_locked()
            frontier_rows = []
            for p, entry in sorted(self._frontier.items()):
                est = entry["window"].estimate()
                if est is None:
                    continue
                lats = sorted(entry["lat_ms"])
                frontier_rows.append(
                    {
                        "nprobe": p,
                        "recall": est["recall"],
                        "ci_lo": est["ci_lo"],
                        "ci_hi": est["ci_hi"],
                        "comparisons": est["comparisons"],
                        # bulk-probe device latency (compile-excluded);
                        # the serving_latency digests below carry what
                        # /ask pays end to end per tier stage
                        "probe_ms_p50": (
                            round(lats[len(lats) // 2], 3) if lats else None
                        ),
                    }
                )
            counts = {
                "served": self._seq,
                "sampled": self._n_sampled,
                "shadows": self._n_shadows,
                "dropped": self._n_dropped,
                "errors": self._n_errors,
                "pending": len(self._pending),
            }
            applied = self._applied_nprobe
        drift = {}
        if self.registry is not None:
            for name in (
                "retrieve_score_margin",
                "retrieve_query_norm",
                "retrieve_tier_ms_bulk_ivf",
                "retrieve_tier_ms_tail_exact",
                "retrieve_tier_ms_merge",
                "retrieve_tier_ms_fused_probe",
            ):
                s = self.registry.histogram(name).summary()
                if s.get("count"):
                    drift[name] = {
                        k: s.get(k) for k in ("count", "p50", "p95")
                    }
        return {
            "enabled": True,
            "running": self.running,
            "sample_every": self.sample_every,
            "seed": self.seed,
            "recall_target": self.recall_target,
            "counts": counts,
            "estimate": cur_est,
            "current": (
                {"tier": current[0], "nprobe": current[1]}
                if current
                else None
            ),
            "estimates": estimates,
            "frontier": frontier_rows,
            "recommended_nprobe": self.recommended_nprobe(),
            "auto_apply": self.auto_apply,
            "applied_nprobe": applied,
            "drift": drift,
        }

    def telemetry_gauges(self) -> Dict[str, float]:
        """Live gauges for the telemetry sampler (``retrieve_recall_*``
        series on /api/telemetry and both /metrics dialects)."""
        with self._lock:
            current = self._current_key
            est = self._windows[current].estimate() if current else None
            pending = float(len(self._pending))
            nprobe = float(current[1]) if current else 0.0
        out = {
            "retrieve_shadow_pending": pending,
            "retrieve_sample_every": float(self.sample_every),
        }
        if est is not None:
            out["retrieve_recall_estimate"] = float(est["recall"])
            out["retrieve_recall_ci_lo"] = float(est["ci_lo"])
            out["retrieve_recall_ci_hi"] = float(est["ci_hi"])
            out["retrieve_recall_window_n"] = float(est["comparisons"])
            out["retrieve_nprobe_current"] = nprobe
        rec = self.recommended_nprobe()
        if rec is not None:
            out["retrieve_nprobe_recommended"] = float(rec)
        return out


# ---------------------------------------------------------------------------
# process singleton (the serving hooks' lookup point)
# ---------------------------------------------------------------------------

_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[RetrievalObservatory] = None


def get_retrieval_observatory() -> Optional[RetrievalObservatory]:
    """The process observatory, or None when retrieval-quality
    observation is not wired (hooks no-op on None — zero cost)."""
    return _GLOBAL


def set_retrieval_observatory(
    observatory: Optional[RetrievalObservatory],
) -> Optional[RetrievalObservatory]:
    """Swap the process observatory (runtime boot, tests).  Returns the
    previous one; the CALLER owns stopping it."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        prev, _GLOBAL = _GLOBAL, observatory
        return prev
