"""Lexical (inverted-impact) tier: exact-token recall beside the dense store.

Dense-only retrieval misses exact-token clinical queries — MRNs, dotted
phone numbers, hyphenated drug names, French jargon whose embedding
neighborhood is generic (ROADMAP item 3; NAIL, arXiv 2305.14499).  This
module adds a device-resident lexical tier the dense tiers' own mesh
discipline applies to:

* **Clinical tokenizer** (:func:`clinical_tokens`): case-fold, NFKD
  diacritic fold (French "résumé" == "resume"), digit-run joining so
  MRNs/phones survive punctuation ("01.42.34.56" and "01-42-34-56" both
  tokenize to ``0142345678``-style runs), hyphenated drug names emit the
  parts AND the joined form ("co-amoxiclav" -> co, amoxiclav,
  coamoxiclav).
* **Hashed vocabulary**: terms map to ``crc32(token) % vocab_size``
  slots (NEVER the builtin ``hash`` — PYTHONHASHSEED would make the
  index non-replayable, the determinism contract PR 19 audits).
  Collisions are *accounted* (:meth:`LexicalIndex.stats`), not resolved:
  at the default 128k-slot vocab a clinical corpus's few collisions cost
  recall the recallscope shadow scan can measure, which is cheaper than
  chasing pointers on the MXU.
* **Impact tiles**: each row packs its top ``tile_width`` terms as
  ``(term_id int32, impact int8)`` pairs — BM25-style impacts
  ``tf*(k1+1) / (tf + k1*(1-b+b*len/ref_len))`` quantized to int8 at a
  fixed ``(k1+1)/127`` scale.  ``ref_len`` is a config constant, not the
  live average doc length, so :meth:`add` is incremental and replay-
  deterministic (an avgdl-dependent impact would re-score the whole
  corpus on every append).  IDF is applied **query-side** from host
  document frequencies, folded into the f32 query weights together with
  the int8 descale — the device never needs a re-upload when N grows.
* **Mesh sharding**: tiles row-shard over the model axis under
  ``shard_map`` exactly like the int8 IVF tier (``index/ivf.py``), and
  the per-shard top-k merges through the SAME 2-gather budget
  (``ops/topk.py:sharded_topk``) — audited as program family
  ``retrieve_lexical_sharded`` in shard_budget.json: 1x1 collective-free,
  multi-device owes exactly the merge gather pair.
* **Scoring** accumulates in f32 via ``preferred_element_type`` on every
  matmul with an int8 operand (the dtype-flow lint contract).

The tier ingests through the ``VectorStore.register_index_sink`` seam,
so adds/deletes/compactions ride the same journal-replayed path as the
dense store and a crash replay converges both tiers (tests/test_lexical.py).
"""

from __future__ import annotations

import functools
import re
import threading
import unicodedata
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from docqa_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from docqa_tpu.engines.spine import spine_run
from docqa_tpu.ops.topk import sharded_topk
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger, span

log = get_logger("docqa.lexical")

NEG_INF = -1e30

# tile pad sentinel (-1) and query pad sentinel (-2) are DISTINCT: a
# padded query slot must never equality-match a padded tile slot, or
# every pad row would score tile_width phantom hits
_TILE_PAD = -1
_QUERY_PAD = -2

# row-count upload bucket (per shard): tiles re-upload on a version
# bump, so quantizing the padded row count keeps the jit shape stable
# while the corpus grows within a bucket
_ROW_BUCKET = 64

# query-term padding ladder (compiled-program reuse across query lengths)
_QUERY_TERM_BUCKETS = (8, 16, 32, 64)
_QUERY_BATCH_BUCKETS = (1, 4, 16)


# ---------------------------------------------------------------------------
# Clinical tokenizer
# ---------------------------------------------------------------------------

# join punctuation/whitespace BETWEEN digits: "01.42.34" / "01-42-34" /
# "01 42 34" -> "014234" (MRNs, FR phone groups); a letter boundary
# still splits, so "10mg" -> 10, mg stays two tokens
_DIGIT_JOIN = re.compile(r"(?<=\d)[.\-\s](?=\d)")
_TOKEN = re.compile(r"[a-z0-9]+")
_HYPHEN_WORD = re.compile(r"[a-z0-9]+(?:-[a-z0-9]+)+")


def clinical_tokens(text: str) -> List[str]:
    """Normalize + tokenize one document or query (EN/FR clinical text).

    case-fold -> NFKD + combining-mark strip (diacritic fold) -> digit-run
    join -> ``[a-z0-9]+`` split, plus one joined token per hyphenated
    compound.  Pure function of the text — no corpus state — so document
    and query tokenization can never drift."""
    if not text:
        return []
    t = unicodedata.normalize("NFKD", text.casefold())
    t = "".join(ch for ch in t if not unicodedata.combining(ch))
    t = _DIGIT_JOIN.sub("", t)
    toks = _TOKEN.findall(t)
    for m in _HYPHEN_WORD.finditer(t):
        toks.append(m.group(0).replace("-", ""))
    return toks


def term_slot(token: str, vocab_size: int) -> int:
    """Deterministic hashed vocab slot (crc32, not builtin ``hash`` —
    the replay witness runs under two PYTHONHASHSEEDs)."""
    return zlib.crc32(token.encode("utf-8")) % vocab_size


# ---------------------------------------------------------------------------
# Device kernels
# ---------------------------------------------------------------------------


def _score_lexical(term_ids, impacts, row_live, q_terms, q_weights):
    """Impact-tile scoring for a batch of term-encoded queries.

    term_ids [R, W] int32 (pad -1), impacts [R, W] int8, row_live [R]
    bool, q_terms [Q, T] int32 (pad -2), q_weights [Q, T] f32 (idf *
    query-tf * int8 descale; pad 0).  Returns scores [Q, R] f32 with
    dead/pad rows at -inf.

    Per query: an equality match ``q_terms == term_ids`` selects each
    row's matching impact slots; contracting the tile axis with int8
    ones and the term axis with the f32 weights are both MXU matmuls
    accumulating in f32 (``preferred_element_type`` — the dtype-flow
    contract)."""
    ones_w = jnp.ones((impacts.shape[1],), jnp.int8)

    def one_query(qt, qw):
        eq = qt[:, None, None] == term_ids[None, :, :]  # [T, R, W]
        masked = jnp.where(eq, impacts[None, :, :], jnp.int8(0))
        per_term = jax.lax.dot_general(
            masked, ones_w, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [T, R]
        return jax.lax.dot_general(
            qw, per_term, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [R]

    scores = jax.vmap(one_query)(q_terms, q_weights)  # [Q, R]
    return jnp.where(row_live[None, :], scores, NEG_INF)


def _lexical_kernel(term_ids, impacts, row_live, q_terms, q_weights, *, k: int):
    """Single-device lexical top-k: score -> ``lax.top_k``.  Collective-
    free (shard_budget.json family ``retrieve_lexical_sharded`` @ 1x1)."""
    scores = _score_lexical(term_ids, impacts, row_live, q_terms, q_weights)
    return jax.lax.top_k(scores, min(k, scores.shape[-1]))


def _lexical_kernel_sharded(
    term_ids, impacts, row_live, q_terms, q_weights, *, k: int, axis: str
):
    """``shard_map`` body: each shard scores only the tile rows it owns,
    then the per-shard candidates (global row ids via the shard offset)
    merge through ``sharded_topk`` — exactly the 2-gather (vals + ids)
    budget the dense tiers pay, nothing else."""
    r_local = term_ids.shape[0]
    shard = jax.lax.axis_index(axis)
    scores = _score_lexical(term_ids, impacts, row_live, q_terms, q_weights)
    return sharded_topk(scores, shard * r_local, k, axis)


def lexical_specs(model_axis: str) -> Tuple[P, ...]:
    """``shard_map`` in_specs for the lexical kernel's five operands:
    tiles/impacts/liveness row-sharded over the model axis, the term-
    encoded queries replicated.  Shared by ``LexicalIndex._get_fn``, the
    hybrid fused program (``engines/retrieve.py``) and the shard audit
    (``analysis/shard_audit.py:retrieve_lexical_sharded``) so the
    audited layout IS the serving layout."""
    return (
        P(model_axis, None),  # term_ids [R, W]
        P(model_axis, None),  # impacts [R, W]
        P(model_axis),  # row_live [R]
        P(),  # q_terms (replicated)
        P(),  # q_weights (replicated)
    )


def build_lexical_search_program(mesh, k: int):
    """The lexical search program: impact-tile scoring -> exact top-k
    (sharded merge kernel when the mesh has model parallelism).  Returns
    the un-jitted callable with arity (term_ids, impacts, row_live,
    q_terms, q_weights) so both :class:`LexicalIndex` (which jits it per
    k) and the sharding audit (``analysis/shard_audit.py`` program
    ``retrieve_lexical_sharded``, which lowers it on virtual meshes to
    count its collectives against ``shard_budget.json``) build the exact
    same program."""
    sharded = mesh is not None and mesh.n_model > 1
    if not sharded:
        return functools.partial(_lexical_kernel, k=k)
    kernel = functools.partial(
        _lexical_kernel_sharded, k=k, axis=mesh.model_axis
    )

    def lexical_body(term_ids, impacts, row_live, q_terms, q_weights):
        return kernel(term_ids, impacts, row_live, q_terms, q_weights)

    return shard_map(
        lexical_body,
        mesh=mesh.mesh,
        in_specs=lexical_specs(mesh.model_axis),
        out_specs=(P(), P()),
        check_vma=False,
    )


def _bucket(n: int, ladder: Sequence[int]) -> int:
    for b in ladder:
        if n <= b:
            return b
    return ladder[-1]


# ---------------------------------------------------------------------------
# LexicalIndex
# ---------------------------------------------------------------------------


class LexicalIndex:
    """Incremental device-resident lexical tier over hashed impact tiles.

    Host master copy (int32 term ids, int8 impacts, f32 unquantized
    impacts for the exact shadow reference, bool liveness) grows under a
    lock exactly like ``VectorStore``; the device copy is a version-
    checked padded snapshot uploaded lazily on the ``lexical_search``
    spine stage.  Rows are addressed by the **dense store's row ids** —
    the tier ingests through ``VectorStore.register_index_sink``, so
    adds, tombstones and compaction renumbering stay in lockstep with
    the dense tier by construction (journal replay converges both).
    """

    def __init__(
        self,
        *,
        vocab_size: int = 1 << 17,
        tile_width: int = 32,
        k1: float = 1.5,
        b: float = 0.75,
        ref_len: int = 64,
        mesh=None,  # runtime.mesh MeshContext: shard tiles over model
    ) -> None:
        if vocab_size < 2:
            raise ValueError("vocab_size must be >= 2")
        if tile_width < 1:
            raise ValueError("tile_width must be >= 1")
        self.vocab_size = int(vocab_size)
        self.tile_width = int(tile_width)
        self.k1 = float(k1)
        self.b = float(b)
        self.ref_len = max(1, int(ref_len))
        self.mesh = mesh
        self._sharded = mesh is not None and mesh.n_model > 1
        self._lock = threading.RLock()
        cap = 0
        self._term_ids = np.full((cap, tile_width), _TILE_PAD, np.int32)
        self._impacts = np.zeros((cap, tile_width), np.int8)
        self._impacts_f = np.zeros((cap, tile_width), np.float32)
        self._live = np.zeros((cap,), bool)
        self._count = 0
        self._df = np.zeros((self.vocab_size,), np.int64)
        self._n_docs = 0  # docs that contributed df (includes deleted)
        self._slot_owner: Dict[int, str] = {}
        self._collided_slots: set = set()
        self._n_truncated_terms = 0
        self._n_empty_docs = 0
        self._version = 0
        # device snapshot: (version, r_pad, term_ids, impacts, row_live)
        self._dev: Optional[Tuple[Any, ...]] = None
        self._fns: Dict[int, Any] = {}

    # -- ingest (VectorStore index-sink protocol) ---------------------------

    def on_add(self, row_ids: Sequence[int], metadata: Sequence[Dict[str, Any]]):
        """Index-sink add hook: rows appended to the dense store arrive
        here with their store row ids and metadata (text under
        ``text_content``, the pipeline's chunk payload key)."""
        texts = [
            str((md or {}).get("text_content", "") or "") for md in metadata
        ]
        self.add(row_ids, texts)
        # snapshot-restore replays tombstoned rows through add() with
        # ``deleted`` set in their metadata — mirror the dense mask
        dead = [
            rid
            for rid, md in zip(row_ids, metadata)
            if (md or {}).get("deleted")
        ]
        if dead:
            self.on_delete(dead)

    def on_delete(self, row_ids: Sequence[int]) -> None:
        """Index-sink tombstone hook (mirrors the dense ``_deleted`` mask)."""
        with self._lock:
            for rid in row_ids:
                if 0 <= rid < self._count:
                    self._live[rid] = False
            self._version += 1

    def on_compact(self, keep: np.ndarray) -> None:
        """Index-sink compaction hook: ``keep`` is the dense store's
        boolean keep-mask over its pre-compaction rows; surviving rows
        renumber to ``np.nonzero(keep)`` order — the same renumbering
        the store applies, so row ids stay aligned."""
        keep = np.asarray(keep, bool)
        with self._lock:
            k = keep[: self._count]
            self._term_ids = self._term_ids[: self._count][k].copy()
            self._impacts = self._impacts[: self._count][k].copy()
            self._impacts_f = self._impacts_f[: self._count][k].copy()
            self._live = self._live[: self._count][k].copy()
            self._count = int(k.sum())
            self._version += 1

    def add(self, row_ids: Sequence[int], texts: Sequence[str]) -> None:
        """Incremental add: tokenize, accumulate per-slot tf, keep the
        top ``tile_width`` impacts per row.  Impacts use the FIXED
        ``ref_len`` (not live avgdl) so an append never re-scores
        existing rows — the replay-determinism requirement."""
        if len(row_ids) != len(texts):
            raise ValueError("row_ids and texts must align")
        if not row_ids:
            return
        with self._lock, span("lexical_add", DEFAULT_REGISTRY):
            top = max(max(row_ids) + 1, self._count)
            self._ensure_capacity(top)
            for rid, text in zip(row_ids, texts):
                self._add_one_locked(int(rid), text)
            self._count = max(self._count, top)
            self._version += 1

    def _ensure_capacity(self, n: int) -> None:
        cap = len(self._live)
        if n <= cap:
            return
        new_cap = max(64, cap * 2, n)
        w = self.tile_width

        def grow(arr, fill, dtype):
            out = np.full((new_cap, w), fill, dtype) if arr.ndim == 2 else (
                np.zeros((new_cap,), dtype)
            )
            out[: len(arr)] = arr
            return out

        self._term_ids = grow(self._term_ids, _TILE_PAD, np.int32)
        self._impacts = grow(self._impacts, 0, np.int8)
        self._impacts_f = grow(self._impacts_f, 0, np.float32)
        self._live = grow(self._live, False, bool)

    def _add_one_locked(self, rid: int, text: str) -> None:
        toks = clinical_tokens(text)
        self._live[rid] = True
        self._term_ids[rid, :] = _TILE_PAD
        self._impacts[rid, :] = 0
        self._impacts_f[rid, :] = 0.0
        if not toks:
            self._n_empty_docs += 1
            return
        tf: Dict[int, int] = {}
        for tok in toks:
            s = term_slot(tok, self.vocab_size)
            tf[s] = tf.get(s, 0) + 1
            owner = self._slot_owner.get(s)
            if owner is None:
                self._slot_owner[s] = tok
            elif owner != tok:
                self._collided_slots.add(s)
        dl = len(toks)
        k1, b = self.k1, self.b
        norm = k1 * (1.0 - b + b * dl / self.ref_len)
        pairs = []  # (impact f32, slot)
        for s, f in tf.items():
            pairs.append((f * (k1 + 1.0) / (f + norm), s))
        # deterministic tie-break on the slot id (dict order is insertion
        # order, itself deterministic, but be explicit)
        pairs.sort(key=lambda p: (-p[0], p[1]))
        if len(pairs) > self.tile_width:
            self._n_truncated_terms += len(pairs) - self.tile_width
            pairs = pairs[: self.tile_width]
        for j, (imp, s) in enumerate(pairs):
            self._term_ids[rid, j] = s
            self._impacts_f[rid, j] = imp
            q = int(round(127.0 * imp / (k1 + 1.0)))
            self._impacts[rid, j] = max(1, min(127, q))
            self._df[s] += 1
        self._n_docs += 1

    # -- query encoding -----------------------------------------------------

    def _descale(self) -> float:
        """Folds the int8 impact quantization back out on the query side."""
        return (self.k1 + 1.0) / 127.0

    def _encode_query_locked(self, text: str) -> List[Tuple[int, float]]:
        """(slot, weight) pairs for one query: weight = query-tf * idf *
        int8-descale.  Slots no live document ever emitted are dropped
        (they can only score 0)."""
        tf: Dict[int, int] = {}
        for tok in clinical_tokens(text):
            s = term_slot(tok, self.vocab_size)
            tf[s] = tf.get(s, 0) + 1
        n = max(self._n_docs, 1)
        descale = self._descale()
        out = []
        for s, f in tf.items():
            df = int(self._df[s])
            if df == 0:
                continue
            idf = float(np.log(1.0 + (n - df + 0.5) / (df + 0.5)))
            out.append((s, f * idf * descale))
        # widest-impact terms first so the bucket truncation (rare: >64
        # distinct query terms) drops the least informative ones
        out.sort(key=lambda p: (-p[1], p[0]))
        return out[: _QUERY_TERM_BUCKETS[-1]]

    def encode_queries(
        self, texts: Sequence[str]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Term-encode a query batch to padded device operands
        ``(q_terms [Q, T] int32, q_weights [Q, T] f32)`` — also the
        operands the hybrid fused program takes (engines/retrieve.py)."""
        with self._lock:
            enc = [self._encode_query_locked(t) for t in texts]
        t_pad = _bucket(max((len(e) for e in enc), default=1) or 1,
                        _QUERY_TERM_BUCKETS)
        # batch axis: same overflow convention as the dense marshaller
        # (engines/encoder.py marshal_texts) — bucket inside the ladder,
        # exact size beyond it, never a silent truncation
        n_q = max(len(texts), 1)
        q_pad = (
            _bucket(n_q, _QUERY_BATCH_BUCKETS)
            if n_q <= _QUERY_BATCH_BUCKETS[-1]
            else n_q
        )
        q_terms = np.full((q_pad, t_pad), _QUERY_PAD, np.int32)
        q_weights = np.zeros((q_pad, t_pad), np.float32)
        for i, pairs in enumerate(enc):
            for j, (s, w) in enumerate(pairs):
                q_terms[i, j] = s
                q_weights[i, j] = w
        return q_terms, q_weights

    # -- device snapshot ----------------------------------------------------

    def _padded_rows(self, count: int) -> int:
        n_shards = self.mesh.n_model if self._sharded else 1
        chunk = _ROW_BUCKET * n_shards
        return max(chunk, -(-count // chunk) * chunk)

    def device_tiles(self):
        """Version-checked device snapshot ``(term_ids, impacts,
        row_live, count)`` — uploads (bounded, on the background rebuild
        stream) only when the host copy moved.  Returns None while the
        tier is empty."""
        with self._lock:
            count = self._count
            version = self._version
            if count == 0:
                return None
            dev = self._dev
            if dev is not None and dev[0] == version:
                return dev[1:]
            r_pad = self._padded_rows(count)
            w = self.tile_width
            term_ids = np.full((r_pad, w), _TILE_PAD, np.int32)
            impacts = np.zeros((r_pad, w), np.int8)
            live = np.zeros((r_pad,), bool)
            term_ids[:count] = self._term_ids[:count]
            impacts[:count] = self._impacts[:count]
            live[:count] = self._live[:count]

        def _upload_on_lane():
            # returns the uploaded arrays: strict mode must sync every
            # transfer before the lane frees (index/ivf.py discipline)
            if self._sharded:
                m = self.mesh
                specs = lexical_specs(m.model_axis)

                def put(arr, spec):
                    return jax.device_put(arr, NamedSharding(m.mesh, spec))

                return (
                    put(term_ids, specs[0]),
                    put(impacts, specs[1]),
                    put(live, specs[2]),
                )
            return (
                jnp.asarray(term_ids),
                jnp.asarray(impacts),
                jnp.asarray(live),
            )

        dev_arrays = spine_run(
            "lexical_search", _upload_on_lane, stream="rebuild"
        )
        snapshot = (version, *dev_arrays, count)
        with self._lock:
            # publish only if nothing moved during the upload; a racing
            # add re-uploads on its next search, and THIS search still
            # serves the consistent snapshot it just built
            if self._version == version:
                self._dev = snapshot
        return snapshot[1:]

    def _get_fn(self, k: int):
        fn = self._fns.get(k)
        if fn is None:
            fn = jax.jit(build_lexical_search_program(
                self.mesh if self._sharded else None, k
            ))
            self._fns[k] = fn
        return fn

    # -- search -------------------------------------------------------------

    def search(
        self, texts: Sequence[str], k: int = 10
    ) -> List[List[Tuple[float, int]]]:
        """Per query, ``(score, row_id)`` pairs ranked by lexical impact
        score; rows with no term overlap (score <= 0) are dropped —
        lexical evidence is exact-match evidence, an all-miss row is not
        a result.  One device dispatch on the ``lexical_search`` stage."""
        if not len(texts):
            return []
        tiles = self.device_tiles()
        if tiles is None:
            return [[] for _ in texts]
        term_ids, impacts, row_live, count = tiles
        q_terms, q_weights = self.encode_queries(texts)
        if not (q_terms != _QUERY_PAD).any():
            # no query term exists in the corpus: skip the dispatch
            return [[] for _ in texts]
        k_eff = min(k, count)
        fn = self._get_fn(k_eff)

        def _lexical_on_lane():
            v, i = fn(
                term_ids, impacts, row_live,
                jnp.asarray(q_terms), jnp.asarray(q_weights),
            )
            return np.asarray(v, np.float32), np.asarray(i)

        with span("lexical_search", DEFAULT_REGISTRY):
            vals, ids = spine_run("lexical_search", _lexical_on_lane)
        out: List[List[Tuple[float, int]]] = []
        for qi in range(len(texts)):
            row = []
            for score, rid in zip(vals[qi], ids[qi]):
                if score <= 0.0 or rid < 0 or rid >= count:
                    continue
                row.append((float(score), int(rid)))
            out.append(row)
        return out

    def host_topk(
        self,
        texts: Sequence[str],
        k: int,
        count_cap: Optional[int] = None,
    ) -> List[List[Tuple[int, float]]]:
        """Exact host-side reference scoring (full-precision f32
        impacts, no int8 quantization, no tile-width device layout
        shortcuts beyond the per-row truncation that defines the tier):
        the recallscope shadow ground truth for the ``lexical`` tier.
        ``count_cap`` freezes the row horizon at what the served
        dispatch saw."""
        with self._lock:
            count = self._count if count_cap is None else min(
                count_cap, self._count
            )
            term_ids = self._term_ids[:count].copy()
            impacts_f = self._impacts_f[:count].copy()
            live = self._live[:count].copy()
            enc = [self._encode_query_locked(t) for t in texts]
        out: List[List[Tuple[int, float]]] = []
        descale = self._descale()
        for pairs in enc:
            if count == 0 or not pairs:
                out.append([])
                continue
            scores = np.zeros((count,), np.float32)
            for s, w in pairs:
                # w folds the int8 descale in; the f32 reference undoes
                # it so ground truth scores full-precision impacts
                hit = term_ids == s  # [count, W]
                scores += (w / descale) * (impacts_f * hit).sum(axis=1)
            scores[~live] = NEG_INF
            order = np.argsort(-scores, kind="stable")[:k]
            out.append(
                [(int(r), float(scores[r])) for r in order if scores[r] > 0.0]
            )
        return out

    # -- accounting ---------------------------------------------------------

    def index_bytes(self) -> Dict[str, Any]:
        """Device-resident byte accounting (``/api/retrieval`` surface)."""
        count = self._count
        r_pad = self._padded_rows(count) if count else 0
        w = self.tile_width
        per_row = w * (4 + 1) + 1  # int32 ids + int8 impacts + bool live
        total = r_pad * per_row
        n_shards = self.mesh.n_model if self._sharded else 1
        return {
            "total_bytes": total,
            "bytes_per_chunk": round(total / max(count, 1), 2),
            "per_shard_bytes": total // n_shards,
            "shards": n_shards,
            "storage": "lexical_int8",
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            live = int(self._live[: self._count].sum())
            return {
                "rows": self._count,
                "live_rows": live,
                "vocab_size": self.vocab_size,
                "tile_width": self.tile_width,
                "hash_collisions": len(self._collided_slots),
                "truncated_terms": self._n_truncated_terms,
                "empty_docs": self._n_empty_docs,
                "version": self._version,
                **self.index_bytes(),
            }
