"""IVF (inverted-file) coarse-quantized search for corpora beyond exact scale.

The reference's only index is exact ``IndexFlatL2`` over 649 vectors
(``semantic-indexer/indexer.py:39,104``).  The exact HBM store
(``index/store.py``) already beats that to ~1M chunks on TPU — one MXU
matmul per query batch is HBM-bandwidth bound, not compute bound.  IVF is
the next decade: probing ``nprobe`` of ``n_clusters`` cells cuts HBM reads
per query by ~``nprobe/n_clusters``, at a measured recall cost.

TPU-first layout (no pointer-chasing inverted lists):

* k-means runs ON DEVICE: assignment is one ``[n, d] x [d, C]`` matmul +
  argmax; the centroid update is a one-hot ``[C, n] x [n, d]`` matmul —
  both MXU shapes.  The build is decomposed into BOUNDED spine work items
  (seeding, one item per Lloyd iteration, one per assignment block) on the
  background ``rebuild`` stream, so a 10M-row build interleaves with
  serving instead of holding a lane — or, in strict mode, the whole
  device — for minutes.
* cells are stored as one dense ``[C, cap, d]`` buffer (uniform capacity,
  padded with zeros; padding rows carry id -1 and score -inf).  Probing is
  a static-shape ``take`` of ``[nprobe, cap, d]`` per query — XLA-friendly,
  no ragged gathers.
* the bulk tier is **int8-quantized tiles with per-row scales** by default
  (``storage="int8"``): ``q = round(v / s)``, ``s = max|v| / 127`` per
  row, scored as ``(q · query) * s`` with f32 accumulation
  (``preferred_element_type`` — the dtype-flow contract).  Per-chunk index
  bytes drop ~4x vs the f32 build buffer (~2x vs a bf16 tier), which is
  what makes 10M chunks HBM-resident on a v5e-8.  The recall cost of the
  quantization is *measured*, not assumed: the recallscope shadow scans
  the full-precision store, so quantization-induced ranking flips show up
  in the online recall estimate (obs/retrieval_observatory.py).
* on a multi-device mesh the cell tensors (tiles, scales, ids) are
  **row-sharded over the model axis** under ``shard_map``: the coarse
  centroid score stays replicated (identical top-nprobe probe list on
  every shard), each shard scores only the probed cells it owns, and the
  per-shard top-k merges through exactly the 2-gather budget the exact
  store's ``sharded_topk`` already pays (vals + ids; gated by
  ``analysis/shard_audit.py`` program ``retrieve_ivf_sharded``).
* cell overflow spills to a small exact buffer (replicated; scored on
  shard 0 only so the merge sees each spill row once), so recall degrades
  gracefully instead of silently dropping rows.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from docqa_tpu.utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from docqa_tpu.engines.spine import spine_run
from docqa_tpu.ops.topk import merge_topk
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger, span

log = get_logger("docqa.ivf")

NEG_INF = -1e30

# assignment-pass block: bounds both device memory and the duration of
# one background work item (a block is one [block, d] x [d, C] matmul)
_ASSIGN_BLOCK = 1 << 18


# ---------------------------------------------------------------------------
# int8 tile quantization
# ---------------------------------------------------------------------------


def quantize_rows_int8(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization: ``q = round(x / s)`` with
    ``s = max|row| / 127``.  Returns ``(q int8, scales float32)`` where
    scales have ``x``'s shape minus the last axis.  Zero rows get scale
    0 (q all zero — dequantization is exact there).

    Round-trip bound: ``|x - q*s| <= s/2 = max|row|/254`` per component
    (tested in tests/test_ivf_sharded.py)."""
    x = np.asarray(x, np.float32)
    amax = np.abs(x).max(axis=-1)
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.rint(x / safe[..., None]), -127, 127).astype(np.int8)
    return q, scale


# ---------------------------------------------------------------------------
# On-device k-means (bounded background work items)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1,))
def _kcenter_init(vectors: jax.Array, c: int):
    """Greedy k-center (farthest-point) seeding, fully on device.

    Random seeding collapses on clustered corpora: by coupon-collector a
    large fraction of natural clusters get no seed, and with near-
    orthogonal clusters Lloyd cannot migrate centroids across them — the
    orphaned clusters' rows scatter over arbitrary cells and coarse
    ranking never finds them (measured recall@10 0.28 at 200k rows /
    2000 natural clusters with random init).  Farthest-point seeding
    covers distinct clusters first by construction.  Cost: ``c``
    sequential [n,d]@[d] matvecs under one jit."""
    n, d = vectors.shape

    def body(i, carry):
        best_sim, chosen = carry
        idx = jnp.argmin(best_sim)  # farthest from every chosen seed
        cvec = vectors[idx]
        chosen = chosen.at[i].set(cvec)
        best_sim = jnp.maximum(best_sim, vectors @ cvec)
        return best_sim, chosen

    best0 = jnp.full((n,), -2.0, vectors.dtype).at[0].set(2.0)
    chosen0 = jnp.zeros((c, d), vectors.dtype).at[0].set(vectors[0])
    best0 = jnp.maximum(best0, vectors @ vectors[0])
    _, chosen = jax.lax.fori_loop(1, c, body, (best0, chosen0))
    return chosen


@jax.jit
def _kmeans_step(vectors: jax.Array, centroids: jax.Array) -> jax.Array:
    """ONE Lloyd iteration.  vectors [n, d] (L2-normalized), centroids
    [C, d]; returns the updated L2-normalized centroids.  One iteration
    per spine work item keeps each background dispatch bounded — the
    old whole-fit ``fori_loop`` was a single device program that, at
    10M-corpus cluster counts, held the device for the entire fit."""
    c = centroids.shape[0]
    scores = vectors @ centroids.T  # [n, C] cosine
    assign = jnp.argmax(scores, axis=1)  # [n]
    onehot = jax.nn.one_hot(assign, c, dtype=vectors.dtype)  # [n, C]
    sums = onehot.T @ vectors  # [C, d]
    counts = jnp.sum(onehot, axis=0)[:, None]  # [C, 1]
    new = sums / jnp.maximum(counts, 1.0)
    # empty cell keeps its old centroid (avoids NaN / collapse)
    new = jnp.where(counts > 0, new, centroids)
    norm = jnp.linalg.norm(new, axis=1, keepdims=True)
    return new / jnp.maximum(norm, 1e-9)


@functools.partial(jax.jit, static_argnums=(2,))
def _assign_block(vectors: jax.Array, centroids: jax.Array, n_assign: int):
    """Top-``n_assign`` nearest cells for one block of rows."""
    scores = jax.lax.dot_general(
        vectors, centroids, (((1,), (1,)), ((), ())),
    )  # [block, C] f32
    return jax.lax.top_k(scores, n_assign)[1]


def kmeans(
    vectors: np.ndarray,
    n_clusters: int,
    n_iters: int = 10,
    seed: int = 0,
    sample: Optional[int] = 262_144,
    n_assign: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fit centroids (on a subsample for huge corpora), assign every row to
    its ``n_assign`` nearest cells.

    Returns (centroids [C, d] float32, assignments [n, n_assign] int32).
    ``n_assign > 1`` is redundant assignment: each row lives in several
    cells, trading cell memory for recall at fixed nprobe (boundary rows
    stop being missable).

    Every device phase queues as a BOUNDED work item on the spine's
    background ``rebuild`` stream: seeding, each Lloyd iteration, and
    each assignment block are separate items, so serving dispatches
    interleave with a 10M-row build instead of waiting minutes behind
    one monolithic item (critical in strict mode, where exactly one
    device program is ever in flight)."""
    vectors = np.asarray(vectors, np.float32)
    n = len(vectors)
    rng = np.random.default_rng(seed)
    fit_on = vectors
    if sample is not None and n > sample:
        fit_on = vectors[rng.choice(n, sample, replace=False)]
    n_assign = min(n_assign, n_clusters)

    def _seed_item():
        # greedy k-center seeding on a bounded subsample (cluster
        # coverage), random fallback only when the corpus is smaller
        # than the seed count
        if len(fit_on) > n_clusters:
            seed_pool = fit_on
            if len(seed_pool) > 65536:
                seed_pool = seed_pool[
                    rng.choice(len(seed_pool), 65536, replace=False)
                ]
            return np.asarray(
                _kcenter_init(jnp.asarray(seed_pool), n_clusters)
            )
        return fit_on[
            rng.choice(
                len(fit_on), n_clusters, replace=n_clusters > len(fit_on)
            )
        ]

    init = spine_run("ivf_build", _seed_item, stream="rebuild")
    fit_dev = spine_run(
        "ivf_build", lambda: jnp.asarray(fit_on), stream="rebuild"
    )
    cent = spine_run(
        "ivf_build", lambda: jnp.asarray(init, jnp.float32),
        stream="rebuild",
    )
    for _ in range(n_iters):
        cent = spine_run(
            "ivf_build", functools.partial(_kmeans_step, fit_dev, cent),
            stream="rebuild",
        )
    # final assignment over the full corpus, one bounded item per block
    assigns = []
    for start in range(0, n, _ASSIGN_BLOCK):
        blk = vectors[start : start + _ASSIGN_BLOCK]

        def _assign_item(blk=blk):
            return np.asarray(_assign_block(jnp.asarray(blk), cent, n_assign))

        assigns.append(spine_run("ivf_build", _assign_item, stream="rebuild"))
    centroids_h = spine_run(
        "ivf_build", lambda: np.asarray(cent, np.float32), stream="rebuild"
    )
    return centroids_h, np.concatenate(assigns).astype(np.int32)


# ---------------------------------------------------------------------------
# probe kernels
# ---------------------------------------------------------------------------


def _coarse_probe(queries, centroids, nprobe: int, n_real_cells):
    """Replicated coarse ranking: top-``nprobe`` cell ids per query.
    ``n_real_cells`` masks zero-padded centroid rows (cell count rounded
    up to the shard count) so padding can never displace a real cell
    from the probe list — the sharded and single-device instances then
    probe identical cells."""
    c_scores = jax.lax.dot_general(
        queries, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, C]
    if n_real_cells is not None and n_real_cells < centroids.shape[0]:
        cols = jax.lax.broadcasted_iota(jnp.int32, c_scores.shape, 1)
        c_scores = jnp.where(cols < n_real_cells, c_scores, NEG_INF)
    return jax.lax.top_k(c_scores, nprobe)[1]  # [q, nprobe]


def _score_probed(queries, cells_g, scale_g, ids_g, valid_g):
    """Score gathered cells against their queries.

    cells_g [q, nprobe, cap, d] (int8 tiles or float), scale_g
    [q, nprobe, cap] f32 per-row scales (None for float storage), ids_g
    [q, nprobe, cap] global row ids (-1 pad), valid_g [q, nprobe] bool
    (None when every gathered cell is live — the single-device path).
    Returns flat per-query (scores [q, nprobe*cap], ids)."""

    def one_query(qv, cq, sq, iq, vq):
        # All scores accumulate to f32 (preferred_element_type) — the
        # contract the dtype-flow lint rule enforces on every matmul
        # with a low-precision operand (docs/STATIC_ANALYSIS.md): a bf16
        # score output loses ~3 significant digits and near-tie rankings
        # with it — measured recall@10 0.91 vs 1.0 (f32 scores) on a
        # clustered 60k corpus with identical cells.  int8 tiles convert
        # inline (-127..127 is exact in bf16) and the per-row scale
        # multiplies the f32 accumulation, so the dequantized score is
        # bit-identical whether the tile lives on one device or a shard.
        s = jnp.einsum(
            "pcd,d->pc", cq.astype(qv.dtype), qv,
            preferred_element_type=jnp.float32,
        )  # [nprobe, cap] f32
        if sq is not None:
            s = s * sq
        live = iq >= 0
        if vq is not None:
            live = live & vq[:, None]
        s = jnp.where(live, s, NEG_INF)
        return s.reshape(-1), iq.reshape(-1)

    if scale_g is None and valid_g is None:
        return jax.vmap(lambda q, c, i: one_query(q, c, None, i, None))(
            queries, cells_g, ids_g
        )
    if valid_g is None:
        return jax.vmap(lambda q, c, s, i: one_query(q, c, s, i, None))(
            queries, cells_g, scale_g, ids_g
        )
    return jax.vmap(one_query)(queries, cells_g, scale_g, ids_g, valid_g)


def _probe_kernel(
    cells: jax.Array,  # [C, cap, d] int8 tiles or float
    cell_scale: Optional[jax.Array],  # [C, cap] f32 (None: float storage)
    cell_ids: jax.Array,  # [C, cap] int32 global row ids (-1 pad)
    centroids: jax.Array,  # [C, d]
    spill: jax.Array,  # [S, d]
    spill_ids: jax.Array,  # [S]
    queries: jax.Array,  # [q, d]
    *,
    nprobe: int,
    k: int,
    n_real_cells: Optional[int] = None,
):
    """Single-device probe: coarse rank -> gather nprobe cells -> score
    -> top-k over cells + spill."""
    probe = _coarse_probe(queries, centroids, nprobe, n_real_cells)
    cell_s, cell_i = _score_probed(
        queries, cells[probe],
        cell_scale[probe] if cell_scale is not None else None,
        cell_ids[probe], None,
    )

    spill_s = jax.lax.dot_general(
        queries, spill, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, S]
    spill_s = jnp.where(spill_ids[None, :] >= 0, spill_s, NEG_INF)

    q_n = queries.shape[0]
    all_s = jnp.concatenate(
        [cell_s, jnp.broadcast_to(spill_s, (q_n, spill_s.shape[1]))], axis=1
    )
    all_i = jnp.concatenate(
        [cell_i,
         jnp.broadcast_to(spill_ids[None, :], (q_n, spill_ids.shape[0]))],
        axis=1,
    )
    vals, pos = jax.lax.top_k(all_s, k)
    return vals, jnp.take_along_axis(all_i, pos, axis=1)


def _probe_kernel_sharded(
    cells: jax.Array,  # [C_local, cap, d] int8 — this shard's tiles
    cell_scale: jax.Array,  # [C_local, cap] f32
    cell_ids: jax.Array,  # [C_local, cap] int32
    centroids: jax.Array,  # [C_pad, d] replicated
    spill: jax.Array,  # [S, d] replicated
    spill_ids: jax.Array,  # [S] replicated
    queries: jax.Array,  # [q, d] replicated
    *,
    nprobe: int,
    k: int,
    n_real_cells: int,
    axis: str,
):
    """``shard_map`` body: mesh-sharded probe with the 2-gather merge.

    The coarse score is replicated (every shard ranks the same
    centroids, so the global top-nprobe probe list is identical
    everywhere); each shard then gathers/scores only the probed cells it
    OWNS — non-local probe slots clamp to local cell 0 and are masked to
    -inf, so per-shard HBM reads stay ~nprobe/n_shards of the tier.
    Local top-k candidates (global row ids) merge through ``all_gather``
    of (vals, ids) + a replicated re-rank — exactly the collective
    content of the exact store's ``sharded_topk``, budgeted as program
    ``retrieve_ivf_sharded`` in shard_budget.json.  Spill rows are
    replicated but scored on shard 0 only, so the merge sees each
    exactly once."""
    c_local = cells.shape[0]
    shard = jax.lax.axis_index(axis)
    probe = _coarse_probe(queries, centroids, nprobe, n_real_cells)
    local = probe - shard * c_local
    valid = (local >= 0) & (local < c_local)  # [q, nprobe]
    safe = jnp.where(valid, local, 0)
    cell_s, cell_i = _score_probed(
        queries, cells[safe], cell_scale[safe], cell_ids[safe], valid
    )

    spill_s = jax.lax.dot_general(
        queries, spill, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, S]
    spill_live = (spill_ids[None, :] >= 0) & (shard == 0)
    spill_s = jnp.where(spill_live, spill_s, NEG_INF)

    q_n = queries.shape[0]
    all_s = jnp.concatenate(
        [cell_s, jnp.broadcast_to(spill_s, (q_n, spill_s.shape[1]))], axis=1
    )
    all_i = jnp.concatenate(
        [cell_i,
         jnp.broadcast_to(spill_ids[None, :], (q_n, spill_ids.shape[0]))],
        axis=1,
    )
    vals, pos = jax.lax.top_k(all_s, k)
    ids = jnp.take_along_axis(all_i, pos, axis=1)
    # the 2-gather top-k merge (vals + ids ride ICI; k*n_shards
    # candidates per query, not the corpus)
    all_vals = jax.lax.all_gather(vals, axis)
    all_ids = jax.lax.all_gather(ids, axis)
    return merge_topk(all_vals, all_ids, k)


def ivf_cell_specs(model_axis: str) -> Tuple[P, ...]:
    """``shard_map`` in_specs for the probe kernel's seven operands:
    cell tiles/scales/ids row-sharded over the model axis, centroids /
    spill / queries replicated.  Shared by ``IVFIndex._get_fn``, the
    fused tiered program (``engines/retrieve.py``) and the shard audit
    (``analysis/shard_audit.py:retrieve_ivf_sharded``) so the audited
    layout IS the serving layout."""
    return (
        P(model_axis, None, None),  # cells [C, cap, d]
        P(model_axis, None),  # cell_scale [C, cap]
        P(model_axis, None),  # cell_ids [C, cap]
        P(),  # centroids (replicated: coarse score everywhere)
        P(),  # spill
        P(),  # spill_ids
        P(),  # queries
    )


# ---------------------------------------------------------------------------
# IVF index
# ---------------------------------------------------------------------------

class IVFIndex:
    """Coarse-quantized cosine search over a fixed corpus snapshot.

    Build once from vectors+metadata (or straight from a ``VectorStore``);
    rebuild periodically as the store grows — the serving pattern (exact
    search over the live append tail + IVF over the compacted bulk, with
    background rebuild and host top-k merge) is implemented by
    ``index/tiered.py:TieredIndex`` and enabled via
    ``StoreConfig.serving_index="tiered"``.

    ``storage="int8"`` (default) stores the cells as int8 tiles with
    per-row scales; ``"float"`` keeps ``dtype`` cells (exact scores, 2x
    the bytes — single-device only).  ``mesh`` with ``n_model > 1``
    row-shards the cell tensors over the model axis and serves through
    the ``shard_map`` merge kernel; sharding requires (and forces) int8
    storage — HBM capacity is the reason the tier shards at all.
    """

    def __init__(
        self,
        vectors: np.ndarray,
        metadata: Sequence[Dict[str, Any]],
        n_clusters: Optional[int] = None,
        nprobe: int = 8,
        cap_factor: float = 1.5,
        n_iters: int = 10,
        seed: int = 0,
        dtype: str = "bfloat16",
        n_assign: int = 2,
        mesh=None,  # runtime.mesh.MeshContext: shard cells over model
        storage: str = "int8",
    ) -> None:
        vectors = np.asarray(vectors, np.float32)
        n, d = vectors.shape
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-9)
        self._meta = list(metadata)
        self.n = n
        self.dim = d
        c = n_clusters or max(1, int(np.sqrt(max(n, 1))))
        self.n_clusters = c
        self.nprobe = min(nprobe, c)
        self.n_assign = max(1, min(n_assign, c))
        self._dtype = jnp.dtype(dtype)
        self.mesh = mesh
        self._sharded = mesh is not None and mesh.n_model > 1
        if self._sharded and storage != "int8":
            # HBM capacity is the point of sharding; a float tier would
            # double shard bytes for recall the shadow estimator could
            # measure the absence of — the sharded tier is int8 tiles.
            log.warning(
                "sharded IVF tier forces int8 storage (requested %r)",
                storage,
            )
            storage = "int8"
        self.storage = storage
        self.n_real_cells = c
        n_shards = mesh.n_model if self._sharded else 1
        # cell rows round up to the shard count for even row shards;
        # padded rows carry zero centroids/tiles and id -1, and the
        # coarse probe masks them (n_real_cells) so they are never
        # probed on any path
        c_pad = -(-c // n_shards) * n_shards
        self.cells_per_shard = c_pad // n_shards

        with span("ivf_build", DEFAULT_REGISTRY):
            # rank more choices than copies: the placement cascade needs
            # fallback cells when a row's best cells are full
            n_choices = max(4, self.n_assign)
            centroids, assign = kmeans(
                vectors, c, n_iters=n_iters, seed=seed,
                n_assign=min(n_choices, c),
            )
            if c_pad != c:
                centroids = np.vstack(
                    [centroids, np.zeros((c_pad - c, d), np.float32)]
                )
            cap = max(8, int(np.ceil(cap_factor * self.n_assign * n / c)))
            cells = np.zeros((c_pad, cap, d), np.float32)
            cell_ids = np.full((c_pad, cap), -1, np.int32)
            fill = np.zeros((c_pad,), np.int64)

            def place(rows: np.ndarray, target_cells: np.ndarray) -> np.ndarray:
                """Vectorized cap-aware placement: rows[i] -> its slot in
                target_cells[i] when the cell has room.  Returns the boolean
                placed-mask.  (The round-1 build looped this in Python over
                1M rows — and let copies overflow into a spill buffer that
                every query then scanned exactly: 22% of a 1M clustered
                corpus spilled, adding ~170 MB of HBM reads per query.)"""
                if len(rows) == 0:
                    return np.zeros((0,), bool)
                order = np.argsort(target_cells, kind="stable")
                tc = target_cells[order]
                # position of each row within its cell group
                group_change = np.r_[True, tc[1:] != tc[:-1]]
                group_start = np.nonzero(group_change)[0]
                within = np.arange(len(tc)) - np.repeat(
                    group_start, np.diff(np.r_[group_start, len(tc)])
                )
                slot = fill[tc] + within
                ok = slot < cap
                r_ok, c_ok, s_ok = rows[order][ok], tc[ok], slot[ok]
                cells[c_ok, s_ok] = vectors[r_ok]
                cell_ids[c_ok, s_ok] = r_ok
                placed_per_cell = np.bincount(c_ok, minlength=c_pad)
                fill[:] = fill + placed_per_cell
                placed = np.zeros((len(rows),), bool)
                placed[order[ok]] = True
                return placed

            # pass 1 — primary copy, cascading to the best cell with room:
            # rank-r failures retry at rank r+1 instead of spilling
            primary_cell = np.full((n,), -1, np.int64)
            pending = np.arange(n)
            # assign has min(n_choices, c) columns — iterate what exists
            # (tiny-c builds with small cap_factor can exhaust every rank
            # and still have pending rows; they spill below)
            for r in range(assign.shape[1]):
                if len(pending) == 0:
                    break
                targets = assign[pending, r]
                placed = place(pending, targets)
                primary_cell[pending[placed]] = targets[placed]
                pending = pending[~placed]
            spill_rows = list(pending)
            # pass 2 — redundant copies (recall: boundary rows reachable
            # from either side), best-effort within remaining capacity.
            # Skip rows whose primary already cascaded into this rank's
            # cell: a duplicate (vector, id) in the same cell burns a slot
            # in exactly the overfull cells the cascade is relieving.
            for r in range(1, self.n_assign):
                everyone = np.arange(n)
                fresh = assign[everyone, r] != primary_cell[everyone]
                rows = everyone[fresh]
                place(rows, assign[rows, r])
            spill_n = max(1, len(spill_rows))
            spill = np.zeros((spill_n, d), np.float32)
            spill_ids = np.full((spill_n,), -1, np.int32)
            for j, i in enumerate(spill_rows):
                spill[j] = vectors[i]
                spill_ids[j] = i
            self.cap = cap
            self.n_spilled = len(spill_rows)

            if storage == "int8":
                cells_up, cell_scale = quantize_rows_int8(cells)
            else:
                cells_up, cell_scale = cells, None
            del cells  # the f32 staging buffer is the build's peak RSS

            def _upload_on_lane():
                # returns the uploaded arrays: strict mode must sync
                # every transfer before the lane frees
                if self._sharded:
                    m = self.mesh
                    specs = ivf_cell_specs(m.model_axis)

                    def put(arr, spec):
                        return jax.device_put(
                            arr, NamedSharding(m.mesh, spec)
                        )

                    self._cells = put(cells_up, specs[0])
                    self._cell_scale = put(cell_scale, specs[1])
                    self._cell_ids = put(cell_ids, specs[2])
                    self._centroids = put(
                        centroids.astype(self._dtype), specs[3]
                    )
                    self._spill = put(spill.astype(self._dtype), specs[4])
                    self._spill_ids = put(spill_ids, specs[5])
                else:
                    self._cells = (
                        jnp.asarray(cells_up)
                        if storage == "int8"
                        else jnp.asarray(cells_up, self._dtype)
                    )
                    self._cell_scale = (
                        jnp.asarray(cell_scale)
                        if cell_scale is not None
                        else None
                    )
                    self._cell_ids = jnp.asarray(cell_ids)
                    self._centroids = jnp.asarray(centroids, self._dtype)
                    self._spill = jnp.asarray(spill, self._dtype)
                    self._spill_ids = jnp.asarray(spill_ids)
                return tuple(
                    a
                    for a in (
                        self._cells, self._cell_scale, self._cell_ids,
                        self._centroids, self._spill, self._spill_ids,
                    )
                    if a is not None
                )

            spine_run("ivf_build", _upload_on_lane, stream="rebuild")
        self._fns: Dict[Tuple[int, int, int], Any] = {}
        log.info(
            "ivf built: n=%d C=%d cap=%d spill=%d nprobe=%d storage=%s "
            "shards=%d bytes/chunk=%.0f",
            n, c, cap, self.n_spilled, self.nprobe, self.storage,
            n_shards, self.index_bytes()["bytes_per_chunk"],
        )

    @classmethod
    def from_store(cls, store, **kw) -> "IVFIndex":
        """Snapshot the live exact store into an IVF index (consistent
        vectors/metadata pair even while the store keeps appending).
        Inherits the store's mesh so the tier shards where the store
        shards."""
        vectors, meta = store.vectors_snapshot()
        kw.setdefault("mesh", store.mesh)
        return cls(vectors, meta, **kw)

    def index_bytes(self) -> Dict[str, Any]:
        """Device-resident byte accounting for the tier — the perf-gate
        ``index_bytes_per_chunk`` structural ceiling and the
        ``/api/retrieval`` capacity surface read this.  ``per_shard`` is
        what ONE device holds (sharded tensors split n_shards ways;
        centroids/spill replicate)."""
        sharded_b = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in (self._cells, self._cell_scale, self._cell_ids)
            if a is not None
        )
        repl_b = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in (self._centroids, self._spill, self._spill_ids)
        )
        n_shards = self.mesh.n_model if self._sharded else 1
        total = sharded_b + repl_b
        return {
            "total_bytes": total,
            "bytes_per_chunk": round(total / max(self.n, 1), 2),
            "per_shard_bytes": sharded_b // n_shards + repl_b,
            "shards": n_shards,
            "storage": self.storage,
        }

    def _get_fn(self, q: int, k: int, nprobe: int):
        key = (q, k, nprobe)
        fn = self._fns.get(key)
        if fn is None:
            if self._sharded:
                m = self.mesh
                kernel = functools.partial(
                    _probe_kernel_sharded,
                    nprobe=nprobe, k=k,
                    n_real_cells=self.n_real_cells,
                    axis=m.model_axis,
                )

                def sharded_probe_body(cells, scale, cids, cent, sp, sp_ids, q):
                    return kernel(cells, scale, cids, cent, sp, sp_ids, q)

                fn = jax.jit(
                    shard_map(
                        sharded_probe_body,
                        mesh=m.mesh,
                        in_specs=ivf_cell_specs(m.model_axis),
                        out_specs=(P(), P()),
                        check_vma=False,
                    )
                )
            else:
                fn = jax.jit(
                    functools.partial(_probe_kernel, nprobe=nprobe, k=k)
                )
            self._fns[key] = fn
        return fn

    def search(
        self,
        queries: np.ndarray,
        k: int = 10,
        nprobe: Optional[int] = None,
        dedup_full: bool = False,
    ) -> List[List[Tuple[float, int, Dict[str, Any]]]]:
        """Returns per query a list of (score, row_id, metadata).

        ``dedup_full``: return every unique candidate the probe fetched
        (up to ``k * (n_assign + 1)`` rows) instead of cutting at ``k``
        — the tiered exact re-rank widens its pool this way so a row the
        quantized ranking pushed just past ``k`` can be recovered at
        full precision (same device program either way)."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        nprobe = min(nprobe or self.nprobe, self.n_clusters)
        k_eff = min(k, self.n)
        # over-fetch when rows live in multiple cells: the raw top list can
        # contain duplicate row ids, which the host dedups back down to k —
        # clamped to the probed candidate pool (top_k beyond it would crash)
        pool = nprobe * self.cap + int(self._spill_ids.shape[0])
        fetch = min(k_eff * (self.n_assign + 1), pool)
        fn = self._get_fn(len(qn), fetch, nprobe)

        def _probe_on_lane():
            v, i = fn(
                self._cells,
                self._cell_scale,
                self._cell_ids,
                self._centroids,
                self._spill,
                self._spill_ids,
                jnp.asarray(qn, self._dtype),
            )
            return np.asarray(v, np.float32), np.asarray(i)

        with span("ivf_search", DEFAULT_REGISTRY):
            vals, ids = spine_run("ivf_search", _probe_on_lane)
        return self._dedup_rows(vals, ids, fetch if dedup_full else k_eff)

    def _dedup_rows(
        self, vals: np.ndarray, ids: np.ndarray, k_eff: int
    ) -> List[List[Tuple[float, int, Dict[str, Any]]]]:
        """Host dedup of the raw top list (rows assigned to multiple
        cells appear once per probed copy) down to k_eff per query —
        shared by :meth:`search` and :meth:`timed_probe`."""
        out = []
        for qi in range(len(vals)):
            row = []
            seen = set()
            for score, rid in zip(vals[qi], ids[qi]):
                if rid < 0 or score <= NEG_INF / 2 or int(rid) in seen:
                    continue
                seen.add(int(rid))
                row.append((float(score), int(rid), self._meta[int(rid)]))
                if len(row) >= k_eff:
                    break
            out.append(row)
        return out

    def timed_probe(
        self,
        queries: np.ndarray,
        k: int = 10,
        nprobe: Optional[int] = None,
        dedup_full: bool = False,
    ) -> Tuple[List[List[Tuple[int, float]]], float, bool]:
        """One coarse probe at an explicit ``nprobe`` as a BACKGROUND
        work item, timed on the lane — the retrieval observatory's
        nprobe-frontier instrument (``obs/retrieval_observatory.py``).

        Returns ``(rows, seconds, fresh_compile)`` where rows are
        per-query ``(row_id, score)`` pairs and ``seconds`` covers
        dispatch + device + fetch as measured AROUND the device phase on
        the lane (queue wait excluded — the frontier's latency axis must
        reflect the probe, not background-stream scheduling).  The first
        call at a new (batch, k, nprobe) shape traces+compiles inside
        the timed window; ``fresh_compile`` flags exactly those samples
        so the observatory can exclude them from the latency axis (a
        per-nprobe first-sample drop would miss later compiles at new
        batch sizes).  Works identically against the sharded tier — the
        probe fn is the shard_map merge kernel there."""
        from time import perf_counter

        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        nprobe = min(nprobe or self.nprobe, self.n_clusters)
        k_eff = min(k, self.n)
        pool = nprobe * self.cap + int(self._spill_ids.shape[0])
        fetch = min(k_eff * (self.n_assign + 1), pool)
        # a cached wrapper has been invoked (and so compiled) before:
        # search() and timed_probe() both go through _get_fn and always
        # call the fn they get back
        fresh_compile = (len(qn), fetch, nprobe) not in self._fns
        fn = self._get_fn(len(qn), fetch, nprobe)

        def _shadow_probe_on_lane():
            t0 = perf_counter()
            v, i = fn(
                self._cells,
                self._cell_scale,
                self._cell_ids,
                self._centroids,
                self._spill,
                self._spill_ids,
                jnp.asarray(qn, self._dtype),
            )
            v = np.asarray(v, np.float32)
            i = np.asarray(i)
            return v, i, perf_counter() - t0

        vals, ids, seconds = spine_run(
            "retrieve_shadow", _shadow_probe_on_lane, stream="probe"
        )
        rows = [
            [(rid, score) for score, rid, _md in row]
            for row in self._dedup_rows(
                vals, ids, fetch if dedup_full else k_eff
            )
        ]
        return rows, seconds, fresh_compile
