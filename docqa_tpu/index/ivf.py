"""IVF (inverted-file) coarse-quantized search for corpora beyond exact scale.

The reference's only index is exact ``IndexFlatL2`` over 649 vectors
(``semantic-indexer/indexer.py:39,104``).  The exact HBM store
(``index/store.py``) already beats that to ~1M chunks on TPU — one MXU
matmul per query batch is HBM-bandwidth bound, not compute bound.  IVF is
the next decade: probing ``nprobe`` of ``n_clusters`` cells cuts HBM reads
per query by ~``nprobe/n_clusters``, at a measured recall cost.

TPU-first layout (no pointer-chasing inverted lists):

* k-means runs ON DEVICE: assignment is one ``[n, d] x [d, C]`` matmul +
  argmax; the centroid update is a one-hot ``[C, n] x [n, d]`` matmul —
  both MXU shapes, iterated under ``lax.fori_loop`` in a single jit.
* cells are stored as one dense ``[C, cap, d]`` buffer (uniform capacity,
  padded with zeros; padding rows carry id -1 and score -inf).  Probing is
  a static-shape ``take`` of ``[nprobe, cap, d]`` per query — XLA-friendly,
  no ragged gathers.
* cell overflow spills to a small exact buffer that every query also scans,
  so recall degrades gracefully instead of silently dropping rows.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from docqa_tpu.engines.spine import spine_run
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger, span

log = get_logger("docqa.ivf")

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# On-device k-means
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(1,))
def _kcenter_init(vectors: jax.Array, c: int):
    """Greedy k-center (farthest-point) seeding, fully on device.

    Random seeding collapses on clustered corpora: by coupon-collector a
    large fraction of natural clusters get no seed, and with near-
    orthogonal clusters Lloyd cannot migrate centroids across them — the
    orphaned clusters' rows scatter over arbitrary cells and coarse
    ranking never finds them (measured recall@10 0.28 at 200k rows /
    2000 natural clusters with random init).  Farthest-point seeding
    covers distinct clusters first by construction.  Cost: ``c``
    sequential [n,d]@[d] matvecs under one jit."""
    n, d = vectors.shape

    def body(i, carry):
        best_sim, chosen = carry
        idx = jnp.argmin(best_sim)  # farthest from every chosen seed
        cvec = vectors[idx]
        chosen = chosen.at[i].set(cvec)
        best_sim = jnp.maximum(best_sim, vectors @ cvec)
        return best_sim, chosen

    best0 = jnp.full((n,), -2.0, vectors.dtype).at[0].set(2.0)
    chosen0 = jnp.zeros((c, d), vectors.dtype).at[0].set(vectors[0])
    best0 = jnp.maximum(best0, vectors @ vectors[0])
    _, chosen = jax.lax.fori_loop(1, c, body, (best0, chosen0))
    return chosen


@functools.partial(jax.jit, static_argnums=(2, 3))
def _kmeans_fit(vectors: jax.Array, init: jax.Array, n_iters: int, c: int):
    """Lloyd iterations, fully on device.  vectors [n, d] (L2-normalized),
    init [C, d].  Returns (centroids [C, d], assignments [n])."""

    def body(_, centroids):
        scores = vectors @ centroids.T  # [n, C] cosine
        assign = jnp.argmax(scores, axis=1)  # [n]
        onehot = jax.nn.one_hot(assign, c, dtype=vectors.dtype)  # [n, C]
        sums = onehot.T @ vectors  # [C, d]
        counts = jnp.sum(onehot, axis=0)[:, None]  # [C, 1]
        new = sums / jnp.maximum(counts, 1.0)
        # empty cell keeps its old centroid (avoids NaN / collapse)
        new = jnp.where(counts > 0, new, centroids)
        norm = jnp.linalg.norm(new, axis=1, keepdims=True)
        return new / jnp.maximum(norm, 1e-9)

    centroids = jax.lax.fori_loop(0, n_iters, body, init)
    assign = jnp.argmax(vectors @ centroids.T, axis=1)
    return centroids, assign


def kmeans(
    vectors: np.ndarray,
    n_clusters: int,
    n_iters: int = 10,
    seed: int = 0,
    sample: Optional[int] = 262_144,
    n_assign: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Fit centroids (on a subsample for huge corpora), assign every row to
    its ``n_assign`` nearest cells.

    Returns (centroids [C, d] float32, assignments [n, n_assign] int32).
    ``n_assign > 1`` is redundant assignment: each row lives in several
    cells, trading cell memory for recall at fixed nprobe (boundary rows
    stop being missable)."""
    vectors = np.asarray(vectors, np.float32)
    n = len(vectors)
    rng = np.random.default_rng(seed)
    fit_on = vectors
    if sample is not None and n > sample:
        fit_on = vectors[rng.choice(n, sample, replace=False)]
    n_assign = min(n_assign, n_clusters)

    def _fit_on_lane():
        """Device phase (background spine work item): seeding, the
        kmeans fit, and the blocked full-corpus assignment — a
        background IVF rebuild queues for a lane instead of becoming
        another concurrent client stream."""
        # greedy k-center seeding on a bounded subsample (cluster
        # coverage), random fallback only when the corpus is smaller
        # than the seed count
        if len(fit_on) > n_clusters:
            seed_pool = fit_on
            if len(seed_pool) > 65536:
                seed_pool = seed_pool[
                    rng.choice(len(seed_pool), 65536, replace=False)
                ]
            init = np.asarray(_kcenter_init(jnp.asarray(seed_pool), n_clusters))
        else:
            init = fit_on[
                rng.choice(
                    len(fit_on), n_clusters, replace=n_clusters > len(fit_on)
                )
            ]
        centroids, _ = _kmeans_fit(
            jnp.asarray(fit_on), jnp.asarray(init), n_iters, n_clusters
        )
        # final assignment over the full corpus, blocked to bound device
        # memory
        assigns = []
        block = 1 << 18
        cT = centroids.T
        for start in range(0, n, block):
            scores = jnp.asarray(vectors[start : start + block]) @ cT
            _, top = jax.lax.top_k(scores, n_assign)
            assigns.append(np.asarray(top))
        return np.asarray(centroids), assigns

    centroids_h, assigns = spine_run(
        "ivf_build", _fit_on_lane, stream="rebuild"
    )
    return centroids_h, np.concatenate(assigns).astype(np.int32)


# ---------------------------------------------------------------------------
# IVF index
# ---------------------------------------------------------------------------

def _probe_kernel(
    cells: jax.Array,  # [C, cap, d]
    cell_ids: jax.Array,  # [C, cap] int32 global row ids (-1 pad)
    centroids: jax.Array,  # [C, d]
    spill: jax.Array,  # [S, d]
    spill_ids: jax.Array,  # [S]
    queries: jax.Array,  # [q, d]
    *,
    nprobe: int,
    k: int,
):
    # All scores accumulate to f32 (preferred_element_type) — the
    # contract the dtype-flow lint rule now enforces on every matmul
    # with a low-precision operand (docs/STATIC_ANALYSIS.md): a bf16 score
    # output loses ~3 significant digits and near-tie rankings with it —
    # measured recall@10 0.91 vs 1.0 (f32 scores) on a clustered 60k corpus
    # with identical cells; the exact store's kernel already did this.
    c_scores = jax.lax.dot_general(
        queries, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, C]
    _, probe = jax.lax.top_k(c_scores, nprobe)  # [q, nprobe]

    def one_query(qv, cells_q, ids_q):
        # cells_q [nprobe, cap, d], ids_q [nprobe, cap]
        s = jnp.einsum(
            "pcd,d->pc", cells_q, qv, preferred_element_type=jnp.float32
        )  # [nprobe, cap]
        s = jnp.where(ids_q >= 0, s, NEG_INF)
        return s.reshape(-1), ids_q.reshape(-1)

    probed_cells = cells[probe]  # [q, nprobe, cap, d]
    probed_ids = cell_ids[probe]  # [q, nprobe, cap]
    cell_s, cell_i = jax.vmap(one_query)(queries, probed_cells, probed_ids)

    spill_s = jax.lax.dot_general(
        queries, spill, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, S]
    spill_s = jnp.where(spill_ids[None, :] >= 0, spill_s, NEG_INF)

    all_s = jnp.concatenate([cell_s, jnp.broadcast_to(spill_s, (queries.shape[0], spill_s.shape[1]))], axis=1)
    all_i = jnp.concatenate(
        [cell_i, jnp.broadcast_to(spill_ids[None, :], (queries.shape[0], spill_ids.shape[0]))],
        axis=1,
    )
    vals, pos = jax.lax.top_k(all_s, k)
    return vals, jnp.take_along_axis(all_i, pos, axis=1)


class IVFIndex:
    """Coarse-quantized cosine search over a fixed corpus snapshot.

    Build once from vectors+metadata (or straight from a ``VectorStore``);
    rebuild periodically as the store grows — the serving pattern (exact
    search over the live append tail + IVF over the compacted bulk, with
    background rebuild and host top-k merge) is implemented by
    ``index/tiered.py:TieredIndex`` and enabled via
    ``StoreConfig.serving_index="tiered"``.
    """

    def __init__(
        self,
        vectors: np.ndarray,
        metadata: Sequence[Dict[str, Any]],
        n_clusters: Optional[int] = None,
        nprobe: int = 32,
        cap_factor: float = 1.5,
        n_iters: int = 10,
        seed: int = 0,
        dtype: str = "bfloat16",
        n_assign: int = 2,
    ) -> None:
        vectors = np.asarray(vectors, np.float32)
        n, d = vectors.shape
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-9)
        self._meta = list(metadata)
        self.n = n
        self.dim = d
        c = n_clusters or max(1, int(np.sqrt(max(n, 1))))
        self.n_clusters = c
        self.nprobe = min(nprobe, c)
        self.n_assign = max(1, min(n_assign, c))
        self._dtype = jnp.dtype(dtype)

        with span("ivf_build", DEFAULT_REGISTRY):
            # rank more choices than copies: the placement cascade needs
            # fallback cells when a row's best cells are full
            n_choices = max(4, self.n_assign)
            centroids, assign = kmeans(
                vectors, c, n_iters=n_iters, seed=seed,
                n_assign=min(n_choices, c),
            )
            cap = max(8, int(np.ceil(cap_factor * self.n_assign * n / c)))
            cells = np.zeros((c, cap, d), np.float32)
            cell_ids = np.full((c, cap), -1, np.int32)
            fill = np.zeros((c,), np.int64)

            def place(rows: np.ndarray, target_cells: np.ndarray) -> np.ndarray:
                """Vectorized cap-aware placement: rows[i] -> its slot in
                target_cells[i] when the cell has room.  Returns the boolean
                placed-mask.  (The round-1 build looped this in Python over
                1M rows — and let copies overflow into a spill buffer that
                every query then scanned exactly: 22% of a 1M clustered
                corpus spilled, adding ~170 MB of HBM reads per query.)"""
                if len(rows) == 0:
                    return np.zeros((0,), bool)
                order = np.argsort(target_cells, kind="stable")
                tc = target_cells[order]
                # position of each row within its cell group
                group_change = np.r_[True, tc[1:] != tc[:-1]]
                group_start = np.nonzero(group_change)[0]
                within = np.arange(len(tc)) - np.repeat(
                    group_start, np.diff(np.r_[group_start, len(tc)])
                )
                slot = fill[tc] + within
                ok = slot < cap
                r_ok, c_ok, s_ok = rows[order][ok], tc[ok], slot[ok]
                cells[c_ok, s_ok] = vectors[r_ok]
                cell_ids[c_ok, s_ok] = r_ok
                placed_per_cell = np.bincount(c_ok, minlength=c)
                fill[:] = fill + placed_per_cell
                placed = np.zeros((len(rows),), bool)
                placed[order[ok]] = True
                return placed

            # pass 1 — primary copy, cascading to the best cell with room:
            # rank-r failures retry at rank r+1 instead of spilling
            primary_cell = np.full((n,), -1, np.int64)
            pending = np.arange(n)
            # assign has min(n_choices, c) columns — iterate what exists
            # (tiny-c builds with small cap_factor can exhaust every rank
            # and still have pending rows; they spill below)
            for r in range(assign.shape[1]):
                if len(pending) == 0:
                    break
                targets = assign[pending, r]
                placed = place(pending, targets)
                primary_cell[pending[placed]] = targets[placed]
                pending = pending[~placed]
            spill_rows = list(pending)
            # pass 2 — redundant copies (recall: boundary rows reachable
            # from either side), best-effort within remaining capacity.
            # Skip rows whose primary already cascaded into this rank's
            # cell: a duplicate (vector, id) in the same cell burns a slot
            # in exactly the overfull cells the cascade is relieving.
            for r in range(1, self.n_assign):
                everyone = np.arange(n)
                fresh = assign[everyone, r] != primary_cell[everyone]
                rows = everyone[fresh]
                place(rows, assign[rows, r])
            spill_n = max(1, len(spill_rows))
            spill = np.zeros((spill_n, d), np.float32)
            spill_ids = np.full((spill_n,), -1, np.int32)
            for j, i in enumerate(spill_rows):
                spill[j] = vectors[i]
                spill_ids[j] = i
            self.cap = cap
            self.n_spilled = len(spill_rows)

            def _upload_on_lane():
                # returns the uploaded arrays: strict mode must sync
                # every transfer before the lane frees
                self._cells = jnp.asarray(cells, self._dtype)
                self._cell_ids = jnp.asarray(cell_ids)
                self._centroids = jnp.asarray(centroids, self._dtype)
                self._spill = jnp.asarray(spill, self._dtype)
                self._spill_ids = jnp.asarray(spill_ids)
                return (self._cells, self._cell_ids, self._centroids,
                        self._spill, self._spill_ids)

            spine_run("ivf_build", _upload_on_lane, stream="rebuild")
        self._fns: Dict[Tuple[int, int, int], Any] = {}
        log.info(
            "ivf built: n=%d C=%d cap=%d spill=%d nprobe=%d",
            n, c, cap, self.n_spilled, self.nprobe,
        )

    @classmethod
    def from_store(cls, store, **kw) -> "IVFIndex":
        """Snapshot the live exact store into an IVF index (consistent
        vectors/metadata pair even while the store keeps appending)."""
        vectors, meta = store.vectors_snapshot()
        return cls(vectors, meta, **kw)

    def _get_fn(self, q: int, k: int, nprobe: int):
        key = (q, k, nprobe)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(functools.partial(_probe_kernel, nprobe=nprobe, k=k))
            self._fns[key] = fn
        return fn

    def search(
        self,
        queries: np.ndarray,
        k: int = 10,
        nprobe: Optional[int] = None,
    ) -> List[List[Tuple[float, int, Dict[str, Any]]]]:
        """Returns per query a list of (score, row_id, metadata)."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        nprobe = min(nprobe or self.nprobe, self.n_clusters)
        k_eff = min(k, self.n)
        # over-fetch when rows live in multiple cells: the raw top list can
        # contain duplicate row ids, which the host dedups back down to k —
        # clamped to the probed candidate pool (top_k beyond it would crash)
        pool = nprobe * self.cap + int(self._spill_ids.shape[0])
        fetch = min(k_eff * (self.n_assign + 1), pool)
        fn = self._get_fn(len(qn), fetch, nprobe)

        def _probe_on_lane():
            v, i = fn(
                self._cells,
                self._cell_ids,
                self._centroids,
                self._spill,
                self._spill_ids,
                jnp.asarray(qn, self._dtype),
            )
            return np.asarray(v, np.float32), np.asarray(i)

        with span("ivf_search", DEFAULT_REGISTRY):
            vals, ids = spine_run("ivf_search", _probe_on_lane)
        return self._dedup_rows(vals, ids, k_eff)

    def _dedup_rows(
        self, vals: np.ndarray, ids: np.ndarray, k_eff: int
    ) -> List[List[Tuple[float, int, Dict[str, Any]]]]:
        """Host dedup of the raw top list (rows assigned to multiple
        cells appear once per probed copy) down to k_eff per query —
        shared by :meth:`search` and :meth:`timed_probe`."""
        out = []
        for qi in range(len(vals)):
            row = []
            seen = set()
            for score, rid in zip(vals[qi], ids[qi]):
                if rid < 0 or score <= NEG_INF / 2 or int(rid) in seen:
                    continue
                seen.add(int(rid))
                row.append((float(score), int(rid), self._meta[int(rid)]))
                if len(row) >= k_eff:
                    break
            out.append(row)
        return out

    def timed_probe(
        self,
        queries: np.ndarray,
        k: int = 10,
        nprobe: Optional[int] = None,
    ) -> Tuple[List[List[Tuple[int, float]]], float, bool]:
        """One coarse probe at an explicit ``nprobe`` as a BACKGROUND
        work item, timed on the lane — the retrieval observatory's
        nprobe-frontier instrument (``obs/retrieval_observatory.py``).

        Returns ``(rows, seconds, fresh_compile)`` where rows are
        per-query ``(row_id, score)`` pairs and ``seconds`` covers
        dispatch + device + fetch as measured AROUND the device phase on
        the lane (queue wait excluded — the frontier's latency axis must
        reflect the probe, not background-stream scheduling).  The first
        call at a new (batch, k, nprobe) shape traces+compiles inside
        the timed window; ``fresh_compile`` flags exactly those samples
        so the observatory can exclude them from the latency axis (a
        per-nprobe first-sample drop would miss later compiles at new
        batch sizes)."""
        from time import perf_counter

        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        nprobe = min(nprobe or self.nprobe, self.n_clusters)
        k_eff = min(k, self.n)
        pool = nprobe * self.cap + int(self._spill_ids.shape[0])
        fetch = min(k_eff * (self.n_assign + 1), pool)
        # a cached wrapper has been invoked (and so compiled) before:
        # search() and timed_probe() both go through _get_fn and always
        # call the fn they get back
        fresh_compile = (len(qn), fetch, nprobe) not in self._fns
        fn = self._get_fn(len(qn), fetch, nprobe)

        def _shadow_probe_on_lane():
            t0 = perf_counter()
            v, i = fn(
                self._cells,
                self._cell_ids,
                self._centroids,
                self._spill,
                self._spill_ids,
                jnp.asarray(qn, self._dtype),
            )
            v = np.asarray(v, np.float32)
            i = np.asarray(i)
            return v, i, perf_counter() - t0

        vals, ids, seconds = spine_run(
            "retrieve_shadow", _shadow_probe_on_lane, stream="probe"
        )
        rows = [
            [(rid, score) for score, rid, _md in row]
            for row in self._dedup_rows(vals, ids, k_eff)
        ]
        return rows, seconds, fresh_compile
