"""Tiered serving index: IVF over the compacted bulk + exact over the tail.

This is the composition ``index/ivf.py`` promises: the live ``VectorStore``
stays the single source of truth (appends, snapshots, metadata, filters);
an ``IVFIndex`` is periodically rebuilt from a consistent snapshot and
serves the *bulk* of the corpus with ``nprobe/n_clusters`` of the HBM
reads, while rows appended since the last rebuild — the *tail* — are
scored exactly (they are few, and recall on fresh documents must be 1.0:
"just ingested but unfindable" was the reference's defining race,
``llm-qa/main.py:35`` loads once at startup).

Query plan:

* unfiltered: IVF probe over bulk  ∪  exact matmul over the tail bucket →
  host top-k merge of ~2k candidates;
* filtered (patient snippets): delegate to the exact store — filters
  target small row subsets where masked exact search is both correct and
  cheap, and IVF cells carry no metadata columns;
* rebuild: when the tail outgrows ``rebuild_tail_rows``, a background
  thread rebuilds from ``store.vectors_snapshot()`` and atomically swaps
  ``(ivf, covered)``; serving never blocks on a rebuild.
"""

from __future__ import annotations

import functools
import threading
from time import perf_counter
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from docqa_tpu.engines.spine import spine_run
from docqa_tpu.index.ivf import IVFIndex
from docqa_tpu.index.store import NEG_INF, SearchResult, VectorStore
from docqa_tpu.obs.retrieval_observatory import (
    ShadowJob,
    get_retrieval_observatory,
)
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger, span
from docqa_tpu.utils import round_up

log = get_logger("docqa.tiered")


@functools.partial(jax.jit, static_argnums=(3,))
def _tail_kernel(tail, queries, n_live, k: int):
    """Exact cosine top-k over the padded tail bucket [T, d]."""
    scores = jax.lax.dot_general(
        queries, tail, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, T]
    rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(rows < n_live, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


class TieredIndex:
    """Serving facade over (VectorStore, IVFIndex) with the store's search
    signature — drop-in for ``QAService``."""

    # docqa-lexroute: this surface accepts search(..., mode=, query_texts=)
    # — the QA service's tier-routing opt-in marker
    supports_modes = True

    def __init__(
        self,
        store: VectorStore,
        nprobe: int = 8,
        min_rows: int = 50_000,
        rebuild_tail_rows: int = 100_000,
        n_clusters: Optional[int] = None,
        seed: int = 0,
        storage: str = "int8",
        lexical=None,  # index.lexical.LexicalIndex: the exact-token tier
        hybrid_alpha: float = 0.6,
        default_mode: str = "dense",
    ) -> None:
        self.store = store
        self.nprobe = nprobe
        self.min_rows = min_rows
        self.rebuild_tail_rows = rebuild_tail_rows
        self.n_clusters = n_clusters
        self.seed = seed
        # docqa-lexroute: optional lexical tier + fusion knobs.  The
        # serving default stays "dense" unless the measured hybrid
        # recall CI-low beats dense-only on the labeled exact-token mix
        # (bench ``answer_routing``) — the PR 13 advisory-first rule;
        # hybrid/lexical modes are always available per request.
        self.lexical = lexical
        self.hybrid_alpha = float(hybrid_alpha)
        self.default_mode = default_mode
        # bulk-tier cell format: "int8" (per-row-scaled tiles, the
        # mesh-shardable HBM-resident layout) or "float" (store dtype,
        # exact scores, 2x bytes, single-device only)
        self.storage = storage
        # the active tier is published as ONE tuple (ivf, covered) — readers
        # take a single reference so they can never pair an old IVF with a
        # new watermark (rows in between would vanish from results)
        self._tier: Optional[tuple] = None  # (IVFIndex, covered_rows)
        self._rebuild_lock = threading.Lock()
        self._rebuilding = False
        # the in-flight background rebuild thread, KEPT so close() can
        # join it: the old fire-and-forget `Thread(...).start()` left a
        # daemon thread whose IVF build (a jit kmeans) could still be
        # inside an XLA compile at interpreter exit — the same
        # std::terminate abort the pool joins its rebuild warmups for
        # (thread-lifecycle true positive, PR 8)
        self._rebuild_thread: Optional[threading.Thread] = None
        # bumped by reset(): a rebuild begun against a pre-reset snapshot
        # must NOT publish (it would resurrect erased vectors and set a
        # stale covered watermark that hides newer rows)
        self._gen = 0
        # device-resident tail: (covered, count, padded_dev, n_live, meta);
        # rebuilt only when the store grows, so queries between appends pay
        # zero host→device traffic
        self._tail_cache: Optional[tuple] = None

    # ---- rebuild -------------------------------------------------------------

    @property
    def covered(self) -> int:
        tier = self._tier
        return tier[1] if tier else 0

    @property
    def tail_rows(self) -> int:
        return self.store.count - self.covered

    def rebuild(self) -> bool:
        """Synchronous rebuild from a consistent store snapshot; returns
        whether an IVF tier is now active (False below ``min_rows`` — exact
        search is already optimal there)."""
        gen = self._gen
        # captured BEFORE the snapshot: a compaction landing between the
        # two reads makes the re-rank guard trip conservatively (skip
        # the exact re-rank) instead of ever matching stale ids
        comp_gen = self.store.compactions
        vectors, meta = self.store.vectors_snapshot()
        if len(vectors) < self.min_rows:
            return self._tier is not None
        with span("tiered_rebuild", DEFAULT_REGISTRY):
            # the tier shards where the store shards: cell tiles ride
            # the same model axis as the exact buffer's row shards, so
            # a mesh serving 10M chunks holds 1/n of the tier per chip
            ivf = IVFIndex(
                vectors,
                meta,
                n_clusters=self.n_clusters,
                nprobe=self.nprobe,
                seed=self.seed,
                dtype=str(self.store.cfg.dtype),
                mesh=self.store.mesh,
                storage=self.storage,
            )
        # the store generation this tier's row ids address (the exact
        # re-rank refuses to index a renumbered host copy)
        ivf._store_compactions = comp_gen
        with self._rebuild_lock:
            if gen != self._gen:
                log.info("discarding rebuild begun before reset()")
                return self._tier is not None
            self._tier = (ivf, len(vectors))  # single-reference publish
        log.info("tiered: ivf tier now covers %d rows", len(vectors))
        return True

    def _maybe_background_rebuild(self) -> None:
        if self.tail_rows < self.rebuild_tail_rows and self._tier is not None:
            return
        if self.store.count < self.min_rows:
            return
        with self._rebuild_lock:
            if self._rebuilding:
                return
            self._rebuilding = True

        def run():
            try:
                self.rebuild()
            except Exception:
                log.exception("tiered rebuild failed")
            finally:
                with self._rebuild_lock:
                    self._rebuilding = False

        t = threading.Thread(target=run, daemon=True, name="ivf-rebuild")
        self._rebuild_thread = t
        t.start()

    def close(self, timeout: float = 60.0) -> None:
        """Join an in-flight background rebuild.  Call on shutdown — an
        IVF build still inside XLA on a daemon thread at interpreter
        exit aborts the process.  The bound is generous because a
        legitimate rebuild is minutes of kmeans at 10M rows; an exceeded
        bound logs and leaks (the pre-close behavior) rather than
        hanging shutdown forever."""
        t = self._rebuild_thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout)
            if t.is_alive():
                log.warning("ivf-rebuild still alive after close() join")

    # ---- search --------------------------------------------------------------

    def _k_bulk(self, k: int, covered: int) -> int:
        """Candidate fetch size for the IVF tier.

        Tombstoned rows are filtered host-side AFTER top-k; without
        headroom a query between rebuilds could return fewer than k live
        results even when enough exist in the tier.  The over-fetch is
        QUANTIZED to {k, 2k, 4k} — a continuously varying fetch would
        recompile the probe/tail kernels on every deletion (both are
        jit-specialized on k) — and backstopped by the exact-search
        fallback in ``_merge`` for the correlated case (deleting one
        document tombstones mutually-similar chunks that cluster at the
        top of the ranking for related queries, which no fraction-based
        headroom can bound)."""
        deleted_frac = self.store.deleted_count / max(self.store.count, 1)
        if deleted_frac == 0:
            return k
        if deleted_frac <= 0.25:
            return min(covered, 2 * k)
        return min(covered, 4 * k)

    def _rerank_active(self, ivf: IVFIndex) -> bool:
        """Whether the exact host re-rank applies to this tier: int8
        storage (float tiers already score exactly) AND the store's
        host copy is still the one the tier's row ids address — a
        ``compact_deleted`` erasure renumbers rows, and between the
        compaction and the operator's ``reset()`` a stale tier must
        fall back to its own (internally consistent) quantized scores
        rather than index the shrunk/renumbered buffer."""
        return (
            ivf.storage == "int8"
            and getattr(ivf, "_store_compactions", None)
            == self.store.compactions
        )

    def _rerank_order(
        self, qn_row: np.ndarray, ids: np.ndarray, k: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """The ONE exact-re-rank core both the serving path and the
        frontier instrument use (they must never drift): true f32
        cosines of ``ids`` against one normalized query from the
        store's host master copy, plus the descending order cut to
        ``k``.  ``store.host_rows`` is lock-free by its append-only
        argument, and ``add()`` stores rows L2-normalized, so one
        [m, d] @ [d] is the true cosine."""
        scores = self.store.host_rows(ids) @ qn_row
        return np.argsort(-scores)[:k], scores

    def _rerank_bulk(
        self,
        queries_n: np.ndarray,
        bulk: List[List[tuple]],
        ivf: IVFIndex,
        k_bulk: int,
    ) -> List[List[tuple]]:
        """Exact f32 re-rank of the int8 tier's candidate pool against
        the store's host master copy, cut back to ``k_bulk``.

        The int8 tiles decide WHICH candidates surface; this confines
        their quantization error to candidate selection — the served
        scores and ranking are full precision, so recall loss only
        occurs when a true top-k row misses the (widened, ``dedup_full``)
        candidate pool entirely.  Skipped (quantized scores served, cut
        to k) for float tiers and across a compaction window
        (:meth:`_rerank_active`).  Host cost: ~``k*(n_assign+1)`` dot
        products per query — noise next to the probe dispatch."""
        if not self._rerank_active(ivf):
            return [row[:k_bulk] for row in bulk]
        out: List[List[tuple]] = []
        for qi, row in enumerate(bulk):
            if not row:
                out.append(row)
                continue
            ids = np.fromiter(
                (rid for _s, rid, _m in row), np.int64, len(row)
            )
            order, scores = self._rerank_order(queries_n[qi], ids, k_bulk)
            out.append(
                [(float(scores[j]), row[j][1], row[j][2]) for j in order]
            )
        return out

    def _merge(
        self,
        queries: np.ndarray,
        bulk: List[List[tuple]],
        tail_vals: np.ndarray,
        tail_ids: np.ndarray,
        tail_meta: List[Dict[str, Any]],
        covered: int,
        k: int,
    ) -> List[List[SearchResult]]:
        """Host-side tier merge: tombstone filter, score sort, and the
        exact fallback for under-filled queries.  Shared by the two-step
        path (``search``) and the fused one-dispatch path
        (``engines/retrieve.py:FusedTieredRetriever``)."""
        out: List[List[SearchResult]] = []
        short: List[int] = []
        for qi in range(len(queries)):
            # tombstoned rows are filtered here between rebuilds (the IVF
            # tier still physically holds them); compaction + reset() is
            # the erasure path
            cands: List[SearchResult] = [
                SearchResult(s, rid, md)
                for s, rid, md in bulk[qi]
                if not md.get("deleted")
            ]
            for s, tid in zip(tail_vals[qi], tail_ids[qi]):
                if s <= NEG_INF / 2:
                    continue
                md = tail_meta[int(tid)]
                if md.get("deleted"):
                    continue
                cands.append(SearchResult(float(s), covered + int(tid), md))
            cands.sort(key=lambda r: -r.score)
            out.append(cands[:k])
            if len(cands) < k:
                short.append(qi)
        if short and (self.store.count - self.store.deleted_count) > 0:
            # under-filled despite the head-room: tombstones clustered at
            # the top of this query's ranking (e.g. a just-deleted document
            # whose chunks all match).  Exact tombstone-masked search is
            # always correct; this path is rare and vanishes at the next
            # compaction/rebuild.
            exact = self.store.search(queries[short], k=k)
            for j, qi in enumerate(short):
                if len(exact[j]) > len(out[qi]):
                    out[qi] = exact[j]
        return out

    def search(
        self,
        queries: np.ndarray,
        k: Optional[int] = None,
        where: Optional[Callable[[Dict[str, Any]], bool]] = None,
        filters: Optional[Dict[str, Any]] = None,
        mode: Optional[str] = None,
        query_texts: Optional[List[str]] = None,
    ) -> List[List[SearchResult]]:
        """Mode-aware retrieval (docqa-lexroute): ``mode`` is one of
        ``dense`` (the embedding tiers, unchanged), ``lexical`` (the
        exact-token impact tier alone), or ``hybrid`` (both, fused by
        ``engines.router.fuse_scores``).  Lexical evidence needs the raw
        ``query_texts`` (the clinical tokenizer runs on text, not
        embeddings); without them — or with metadata filters, which only
        the dense store implements — non-dense modes fall back to dense
        and count ``retrieve_mode_fallback``."""
        k_final = k or self.store.cfg.default_k
        mode = self._resolve_mode(mode, query_texts, where, filters)
        DEFAULT_REGISTRY.counter(f"retrieve_mode_{mode}").inc()
        if mode == "lexical":
            return self._search_lexical(query_texts, k_final)
        dense = self._search_dense(
            queries, k, where, filters, observe=mode == "dense"
        )
        if mode == "dense":
            return dense
        return self._fuse_hybrid(queries, query_texts, dense, k_final)

    def _resolve_mode(self, mode, query_texts, where, filters) -> str:
        mode = mode or self.default_mode
        if mode not in ("dense", "lexical", "hybrid"):
            log.warning("unknown retrieve mode %r; serving dense", mode)
            mode = "dense"
        if mode != "dense" and (
            self.lexical is None
            or query_texts is None
            or where is not None
            or filters
        ):
            DEFAULT_REGISTRY.counter("retrieve_mode_fallback").inc()
            return "dense"
        return mode

    def _search_dense(
        self,
        queries: np.ndarray,
        k: Optional[int] = None,
        where: Optional[Callable[[Dict[str, Any]], bool]] = None,
        filters: Optional[Dict[str, Any]] = None,
        observe: bool = True,
    ) -> List[List[SearchResult]]:
        self._maybe_background_rebuild()
        tier = self._tier  # one read: (ivf, covered) stay consistent
        if tier is None or where is not None or filters:
            # filtered or pre-IVF: masked exact search is the right tool
            return self.store.search(queries, k=k, where=where, filters=filters)
        ivf, covered = tier

        k = k or self.store.cfg.default_k
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        k_bulk = self._k_bulk(k, covered)
        with span("tiered_search", DEFAULT_REGISTRY):
            # per-tier latency split (docqa-recallscope): bulk probe /
            # tail scan / host merge each get their own digest, so the
            # nprobe frontier's latency axis can be read against what
            # /ask actually pays per stage (the aggregate retrieve span
            # alone could not attribute a regression to a tier)
            t_stage = perf_counter()
            # one nprobe read: a set_nprobe landing mid-request must not
            # make _observe_quality label this comparison with a value
            # the probe above never used
            nprobe_now = self.nprobe
            qn = queries / np.maximum(
                np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
            )
            bulk = ivf.search(
                queries, k=k_bulk, nprobe=nprobe_now, dedup_full=True
            )
            bulk = self._rerank_bulk(qn, bulk, ivf, k_bulk)
            DEFAULT_REGISTRY.histogram("retrieve_tier_ms_bulk_ivf").observe(
                (perf_counter() - t_stage) * 1e3
            )

            _, _, tail_dev, n_live, tail_meta = self._tail_device(covered)
            t_stage = perf_counter()
            if n_live == 0:
                # empty tail: bulk-only, but still through the merge loop
                # below so the under-fill fallback applies
                vals = np.empty((len(queries), 0), np.float32)
                ids = np.empty((len(queries), 0), np.int32)
            else:
                # tombstone headroom like the bulk fetch, but never below k
                # (k_bulk is capped at `covered`), and NOT clamped to
                # n_live: rows past n_live are NEG_INF-masked and dropped
                # in the merge, so the quantized ladder value keeps ONE
                # compiled tail kernel while the tail grows instead of
                # recompiling per append.  The padded bucket size bounds
                # top_k's k and only changes when the bucket grows.
                k_tail = min(max(k_bulk, k), int(tail_dev.shape[0]))

                def _tail_on_lane():
                    v, i = _tail_kernel(
                        tail_dev,
                        jnp.asarray(qn, jnp.dtype(self.store.cfg.dtype)),
                        jnp.int32(n_live),
                        k_tail,
                    )
                    return np.asarray(v, np.float32), np.asarray(i)

                vals, ids = spine_run("tiered_tail", _tail_on_lane)
            DEFAULT_REGISTRY.histogram("retrieve_tier_ms_tail_exact").observe(
                (perf_counter() - t_stage) * 1e3
            )

        t_stage = perf_counter()
        out = self._merge(
            queries, bulk, vals, ids, tail_meta, covered, k
        )
        DEFAULT_REGISTRY.histogram("retrieve_tier_ms_merge").observe(
            (perf_counter() - t_stage) * 1e3
        )
        if observe:
            # hybrid/lexical modes submit their OWN per-tier shadow jobs
            # (one sampled job per request, labeled with the served tier)
            self._observe_quality(
                queries, out, ivf, covered, covered + n_live, k, nprobe_now
            )
        return out

    # ---- lexical / hybrid serving (docqa-lexroute) ---------------------------

    def _row_meta(self, rid: int) -> Optional[Dict[str, Any]]:
        """Metadata for a lexical-surfaced row id (the dense candidates
        carry theirs already).  Lock-held read of the store's row-aligned
        metadata list."""
        store = self.store
        with store._lock:
            if 0 <= rid < store._count:
                return store._meta[rid]
        return None

    def _search_lexical(
        self, texts: List[str], k: int
    ) -> List[List[SearchResult]]:
        """Pure lexical serving: impact-tile top-k mapped onto the dense
        store's metadata (same row-id space by the index-sink contract),
        tombstones filtered like every tier."""
        lex = self.lexical.search(texts, k=k)
        out: List[List[SearchResult]] = []
        for row in lex:
            res = []
            for score, rid in row:
                md = self._row_meta(rid)
                if md is None or md.get("deleted"):
                    continue
                res.append(SearchResult(float(score), rid, md))
            out.append(res)
        self._observe_lexical(texts, out, k)
        return out

    def _fuse_hybrid(
        self,
        queries: np.ndarray,
        texts: List[str],
        dense: List[List[SearchResult]],
        k: int,
    ) -> List[List[SearchResult]]:
        """Hybrid merge: normalized dense + lexical mix
        (``engines.router.fuse_scores``) over the candidate union, cut
        to ``k``.  The dense candidates were produced by the unchanged
        dense path (nprobe snapshot discipline and all); the lexical
        dispatch is the tier's own single program."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        seen_count = self.store.count  # shadow horizon: pre-fusion view
        t_stage = perf_counter()
        lex = self.lexical.search(texts, k=k)
        DEFAULT_REGISTRY.histogram("retrieve_tier_ms_lexical").observe(
            (perf_counter() - t_stage) * 1e3
        )
        out = self._fuse_rows(dense, lex, k)
        self._observe_hybrid(queries, texts, out, k, seen_count)
        return out

    def _fuse_rows(
        self,
        dense: List[List[SearchResult]],
        lex: List[List[Tuple[float, int]]],
        k: int,
    ) -> List[List[SearchResult]]:
        """The fusion core shared by the two-step path above and the
        one-dispatch fused path (``engines/retrieve.py``, which hands
        in the lexical candidates its own program produced)."""
        from docqa_tpu.engines.router import fuse_scores

        out: List[List[SearchResult]] = []
        for qi, drow in enumerate(dense):
            lrow = lex[qi] if qi < len(lex) else []
            md_by: Dict[int, Dict[str, Any]] = {
                r.row_id: r.metadata for r in drow
            }
            fused = fuse_scores(
                [(r.score, r.row_id) for r in drow],
                lrow,
                self.hybrid_alpha,
            )
            res: List[SearchResult] = []
            for score, rid in fused:
                md = md_by.get(rid)
                if md is None:
                    md = self._row_meta(rid)
                if md is None or md.get("deleted"):
                    continue
                res.append(SearchResult(float(score), rid, md))
                if len(res) >= k:
                    break
            out.append(res)
        return out

    def _observe_lexical(
        self, texts: List[str], out: List[List[SearchResult]], k: int
    ) -> None:
        """Per-tier shadow job for the lexical tier (docqa-recallscope):
        ground truth is the EXACT host-side reference scoring
        (full-precision impacts, ``LexicalIndex.host_topk``), computed
        EAGERLY on sampled requests so the pending job never holds raw
        query text (the PHI rule: jobs hold embeddings and salted
        hashes, never text — a lexical job holds only row/score pairs)."""
        robs = get_retrieval_observatory()
        if robs is None or not robs.sample():
            return
        served = [[(r.row_id, r.score) for r in row] for row in out]
        reference = self.lexical.host_topk(texts, k)

        def shadow_fn():
            return [[(rid, s) for rid, s in row] for row in reference], None

        robs.submit(
            ShadowJob(
                tier="lexical",
                nprobe=0,  # no probe axis on this tier
                k=k,
                served=served,
                shadow_fn=shadow_fn,
            )
        )

    def _observe_hybrid(
        self,
        queries: np.ndarray,
        texts: List[str],
        out: List[List[SearchResult]],
        k: int,
        seen_count: int,
    ) -> None:
        """Per-tier shadow job for the hybrid tier: ground truth fuses
        the store's exact dense shadow scan with the lexical tier's
        exact host reference under the SAME alpha the serving merge
        used, so a fusion-weight drift fires the existing recall SLO.
        The lexical half is computed eagerly (no text in the pending
        job); the dense half runs on the background probe stream as
        usual."""
        robs = get_retrieval_observatory()
        if robs is None or not robs.sample():
            return
        served = [[(r.row_id, r.score) for r in row] for row in out]
        alpha = self.hybrid_alpha
        lex_ref = self.lexical.host_topk(texts, k, count_cap=seen_count)
        q_copy = np.array(queries, np.float32, copy=True)
        store = self.store

        def shadow_fn():
            from docqa_tpu.engines.router import fuse_scores

            rows = store.shadow_search(q_copy, k, count_cap=seen_count)
            fused = []
            for qi, row in enumerate(rows):
                dense_pairs = [(r.score, r.row_id) for r in row]
                lrow = [
                    (s, rid)
                    for rid, s in (lex_ref[qi] if qi < len(lex_ref) else [])
                ]
                fused.append(
                    [
                        (rid, s)
                        for s, rid in fuse_scores(dense_pairs, lrow, alpha, k=k)
                    ]
                )
            return fused, q_copy

        robs.submit(
            ShadowJob(
                tier="hybrid",
                nprobe=0,
                k=k,
                served=served,
                shadow_fn=shadow_fn,
                query_norms=[
                    float(x) for x in np.linalg.norm(q_copy, axis=1)
                ],
                attrs={"alpha": alpha},
            )
        )

    def _observe_quality(
        self,
        queries: np.ndarray,
        out: List[List[SearchResult]],
        ivf: IVFIndex,
        covered: int,
        seen_count: int,
        k: int,
        nprobe: int,
    ) -> None:
        """Shadow-sampling hook (docqa-recallscope): hand the retrieval
        observatory this request's served top-k plus closures that
        reproduce the exact ground truth and the neighbor-nprobe probes
        on the spine's background stream.  ``seen_count`` pins the
        shadow's corpus view to the rows this query could have seen, so
        a concurrent ingest cannot read as a recall miss.  Non-sampled
        calls cost one counter bump and one hash."""
        robs = get_retrieval_observatory()
        if robs is None or not robs.sample():
            return
        served = [[(r.row_id, r.score) for r in row] for row in out]
        margins = [
            row[0].score - row[-1].score for row in out if len(row) >= 2
        ]
        norms = [float(n) for n in np.linalg.norm(queries, axis=1)]
        q_copy = np.array(queries, np.float32, copy=True)
        store = self.store

        def shadow_fn():
            rows = store.shadow_search(q_copy, k, count_cap=seen_count)
            return (
                [[(r.row_id, r.score) for r in row] for row in rows],
                q_copy,
            )

        robs.submit(
            ShadowJob(
                tier="tiered",
                # the nprobe the served probe actually used, not a
                # re-read racing a concurrent set_nprobe
                nprobe=int(min(nprobe, ivf.n_clusters)),
                k=k,
                served=served,
                shadow_fn=shadow_fn,
                frontier_fn=lambda qn, p: self._frontier_probe(
                    ivf, qn, k, p
                ),
                covered=covered,
                n_clusters=ivf.n_clusters,
                query_norms=norms,
                served_margins=margins,
            )
        )

    def _frontier_probe(self, ivf: IVFIndex, queries, k: int, nprobe: int):
        """Frontier probe with SERVING semantics (the recallscope
        ``frontier_fn``): widened candidate pool + the int8 path's exact
        f32 re-rank, so the observed recall/latency frontier measures
        what ``search`` would deliver at that nprobe — the raw quantized
        ranking would understate served recall and recommend a bigger
        nprobe than the target needs.  ``seconds`` stays the device
        probe (the host re-rank is ~µs of numpy)."""
        rows, seconds, fresh = ivf.timed_probe(
            queries, k=k, nprobe=nprobe, dedup_full=True
        )
        if not self._rerank_active(ivf):
            return [r[:k] for r in rows], seconds, fresh
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        qn = q / np.maximum(
            np.linalg.norm(q, axis=1, keepdims=True), 1e-9
        )
        out = []
        for qi, row in enumerate(rows):
            if not row:
                out.append(row)
                continue
            ids = np.fromiter((rid for rid, _s in row), np.int64, len(row))
            order, scores = self._rerank_order(qn[qi], ids, k)
            out.append([(int(ids[j]), float(scores[j])) for j in order])
        return out, seconds, fresh

    def set_nprobe(self, nprobe: int) -> int:
        """Apply a new serving nprobe live — the observatory's
        recommendation hook (``retrieval_quality.auto_apply_nprobe``)
        and the operator's /api/retrieval-guided knob.  Covers both the
        two-step path (reads ``self.nprobe`` per search) and the fused
        program path (reads the active tier's ``ivf.nprobe``); future
        rebuilds inherit it via ``self.nprobe``."""
        n = max(1, int(nprobe))
        tier = self._tier  # one read: (ivf, covered) stay consistent
        # plain int publishes (GIL-atomic): a search mid-flight reads
        # either the old or the new value, both coherent configurations
        self.nprobe = n
        if tier is not None:
            tier[0].nprobe = min(n, tier[0].n_clusters)
        log.info("tiered: serving nprobe set to %d", n)
        return n

    def reset(self) -> None:
        """Drop the IVF tier and tail cache (searches fall back to exact
        until the next rebuild).  Required after ``store.compact_deleted``:
        compaction renumbers rows, and a stale tier would both misattribute
        ids and keep serving erased vectors.  Bumps the generation so an
        in-flight background rebuild (whose snapshot predates the reset)
        discards itself instead of publishing."""
        with self._rebuild_lock:
            self._gen += 1
            self._tier = None
            self._tail_cache = None

    def _tail_device(self, covered: int):
        """Device-resident padded tail, rebuilt only when the store has
        grown — the per-query cost is zero host→device traffic (a naive
        re-upload would move the whole tail across PCIe on every search).
        Returns (covered, count, padded_dev, n_live, meta)."""
        cache = self._tail_cache
        if cache is not None and cache[0] == covered:
            if cache[1] == self.store.count:
                return cache
        gen = self._gen
        vecs, meta = self.store.vectors_snapshot(start=covered)
        n_live = len(vecs)
        bucket = round_up(max(n_live, 1), 4096)  # stable jit shapes
        padded = np.zeros((bucket, self.store.cfg.dim), np.float32)
        padded[:n_live] = vecs
        tail_dev = spine_run(
            "tiered_tail",
            lambda: jnp.asarray(padded, jnp.dtype(self.store.cfg.dtype)),
        )
        cache = (
            covered,
            covered + n_live,
            tail_dev,
            n_live,
            meta,
        )
        # generation-checked publish UNDER the rebuild lock: a serving
        # thread that snapshotted before a concurrent reset() (erasure /
        # compaction) must not write its stale tail back — the pre-PR-8
        # lock-free store could resurrect erased vectors and serve them
        # until the next append invalidated the cache (guarded-state
        # true positive; regression-tested in tests/test_racecheck.py)
        with self._rebuild_lock:
            if gen == self._gen:
                self._tail_cache = cache
        return cache

    def index_stats(self) -> dict:
        """Tier layout + byte accounting for ``/api/retrieval`` and the
        perf gate's ``index_bytes_per_chunk`` structural ceiling."""
        with self._rebuild_lock:
            tier = self._tier
        if tier is None:
            out = {"active": False}
        else:
            ivf, covered = tier
            out = {
                "active": True,
                "covered": covered,
                "n_clusters": ivf.n_clusters,
                "nprobe": self.nprobe,
                "n_assign": ivf.n_assign,
                "cap": ivf.cap,
                "spilled": ivf.n_spilled,
            }
            out.update(ivf.index_bytes())
        if self.lexical is not None:
            out["lexical"] = self.lexical.stats()
            out["retrieve_mode_default"] = self.default_mode
            out["hybrid_alpha"] = self.hybrid_alpha
        return out

    # ---- store passthroughs (QAService drop-in) -----------------------------

    @property
    def count(self) -> int:
        return self.store.count

    def metadata_select(self, limit=None, **filters):
        return self.store.metadata_select(limit=limit, **filters)

    def metadata_rows(self):
        return self.store.metadata_rows()
