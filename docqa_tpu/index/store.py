"""HBM-resident sharded vector store.

Replaces FAISS ``IndexFlatL2`` + pickle metadata + the shared-filesystem
handoff (``semantic-indexer/indexer.py:17-48,26-30``; ``llm-qa/main.py:35-58``).
Reference defects fixed by design (SURVEY §5 "race detection"):

* the indexer rewrote the whole index to disk after **every** message while
  the QA service read the same files unlocked → here both planes share one
  in-process store; snapshots are atomic (write-temp + rename) and versioned;
* the QA service loaded the index **once at startup** → here every search
  sees the current device buffer (device-side append, no restart);
* metadata recorded only a source string (``indexer.py:123``) so
  patient-level retrieval was unimplementable (SURVEY appendix) → here
  metadata carries first-class ``patient_id`` / ``doc_type`` / ``date``.

Device layout: one [capacity, dim] bf16 buffer, rows sharded over the
``model`` mesh axis.  Search = one MXU matmul + per-shard ``lax.top_k`` +
tiny all-gather merge (``ops/topk.py``) under ``shard_map``.  Appends write
into preallocated capacity via donated ``dynamic_update_slice`` — no
reallocation, no recompilation until capacity doubles (shape bucketing,
SURVEY §7 hard part (a)).

Scores are dot products over L2-normalized embeddings == cosine; identical
ranking to the reference's L2-over-MiniLM (SURVEY appendix).
"""

from __future__ import annotations

import functools
import json
import os
import tempfile
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from docqa_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from docqa_tpu.config import StoreConfig
from docqa_tpu.engines.spine import spine_run
from docqa_tpu.ops.topk import sharded_topk
from docqa_tpu.runtime import native
from docqa_tpu.runtime.mesh import MeshContext
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger, span
from docqa_tpu.utils import round_up

log = get_logger("docqa.store")

NEG_INF = -1e30


@dataclass
class SearchResult:
    score: float
    row_id: int
    metadata: Dict[str, Any]


def _search_kernel(
    vectors, queries, count, filter_mask, k: int, axis: str
):
    """Runs inside shard_map.  vectors [n_local, d], queries [q, d] replicated,
    count/filter replicated; returns replicated (vals [q,k], global ids).

    ``filter_mask`` may be ``None``: unfiltered searches skip it entirely —
    the [capacity] bool would otherwise be uploaded host→device on EVERY
    query (a ~1 MB transfer per search at the 1M-row target, worth ~86 ms
    over a tunneled TPU)."""
    n_local = vectors.shape[0]
    shard = jax.lax.axis_index(axis)
    offset = shard * n_local
    scores = jax.lax.dot_general(
        queries,
        vectors,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [q, n_local]
    rows = offset + jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    live = rows < count
    if filter_mask is not None:
        mask_local = jax.lax.dynamic_slice_in_dim(
            filter_mask, offset, n_local, 0
        )
        live = live & mask_local[None, :]
    scores = jnp.where(live, scores, NEG_INF)
    return sharded_topk(scores, offset, k, axis)


def _search_single(vectors, queries, count, filter_mask, k: int):
    scores = jax.lax.dot_general(
        queries, vectors, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    live = rows < count
    if filter_mask is not None:
        live = live & filter_mask[None, :]
    scores = jnp.where(live, scores, NEG_INF)
    return jax.lax.top_k(scores, k)


def _append1_kernel(buf, vals, offset):
    """1-D variant of ``_append_kernel`` for the token-length column."""
    return jax.lax.dynamic_update_slice(buf, vals, (offset,))


def _append_kernel(buf, rows, offset):
    return jax.lax.dynamic_update_slice_in_dim(buf, rows, offset, 0)


_NO_DATE = np.int32(-1)


def _date_code(value: Optional[str]) -> int:
    """ISO ``YYYY-MM-DD`` (or any prefix-ISO string) → sortable int code;
    anything unparseable → -1 (treated as 'no date')."""
    if not value:
        return int(_NO_DATE)
    digits = "".join(c for c in str(value)[:10] if c.isdigit())
    if len(digits) < 8:
        return int(_NO_DATE)
    return int(digits[:8])


class VectorStore:
    """Append + exact-search over device-sharded vectors with host metadata.

    Metadata filters are **columnar**: ``patient_id`` / ``doc_type`` are
    interned to int codes and ``doc_date`` to a sortable int, each kept in a
    capacity-doubling numpy column.  Filtered search builds its device mask
    with vectorized compares — O(1) numpy ops, not an O(corpus) Python
    predicate loop (the round-1 flaw: ~1M Python calls per patient-snippet
    search at the 1M-chunk target)."""

    _FILTER_KEYS = ("patient_id", "doc_type", "date_from", "date_to")

    def __init__(
        self,
        cfg: StoreConfig,
        mesh: Optional[MeshContext] = None,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self._lock = threading.RLock()
        self._meta: List[Dict[str, Any]] = []
        self._host = np.zeros((0, cfg.dim), np.float32)  # durable master copy
        self._count = 0
        self._version = 0
        self._n_shards = mesh.n_model if mesh is not None else 1
        self._capacity = self._round_capacity(cfg.shard_capacity)
        self._dtype = jnp.dtype(cfg.dtype)
        self._dev = self._alloc(self._capacity)
        self._search_fns: Dict[Tuple[int, int, int], Callable] = {}
        self._append_jit = jax.jit(_append_kernel, donate_argnums=(0,))
        # columnar metadata (code -1 == absent; intern code space per column)
        self._codes: Dict[str, Dict[str, int]] = {
            "patient_id": {}, "doc_type": {}, "doc_id": {},
        }
        self._cols: Dict[str, np.ndarray] = {
            "patient_id": np.zeros((0,), np.int32),
            "doc_type": np.zeros((0,), np.int32),
            "doc_id": np.zeros((0,), np.int32),
            "doc_date": np.zeros((0,), np.int32),
        }
        # tombstones: deleted rows stay in HBM (append-only buffer) but are
        # masked out of every search; ``compact_deleted`` erases for real
        self._deleted = np.zeros((0,), bool)
        self._n_deleted = 0
        # compaction generation: the ONLY operation that renumbers rows.
        # Derived indexes that cached row ids (the tiered tier's exact
        # re-rank) compare this against the value they captured at build
        # time — a mismatch means their ids no longer address these rows
        # (see TieredIndex._rerank_active).
        self._n_compactions = 0
        # index sinks (docqa-lexroute): secondary index consumers that
        # must stay row-aligned with THIS store — the lexical tier
        # registers here.  Sinks are notified inside the same locked
        # mutation that commits the dense change, on every path that
        # reaches add/delete/compact — including journal replay and
        # snapshot restore, which re-drive add() — so a crash-replayed
        # ingest converges every tier, not just the dense one.
        self._index_sinks: List[Any] = []
        # Token sidecar (cfg.token_width > 0): per-row generator-token ids
        # + true lengths, row-aligned with the vector buffer through every
        # add/grow/compact/snapshot — the device-side prompt source for
        # the fused RAG path (engines/rag_fused.py).  Row-sharded over the
        # model axis exactly like the vector buffer, so the fused
        # single-sync ask composes with a sharded mesh (the per-shard
        # token gather + psum merge lives in engines/rag_fused.py).
        W = cfg.token_width
        if W:
            self._tok_host = np.zeros((0, W), np.int32)
            self._tok_len_host = np.zeros((0,), np.int32)
            self._tok_dev = self._place_rows(
                jnp.zeros((self._capacity, W), jnp.int32)
            )
            self._tok_len_dev = self._place_rows(
                jnp.zeros((self._capacity,), jnp.int32)
            )
            self._tok_append_jit = jax.jit(
                _append_kernel, donate_argnums=(0,)
            )
            self._tok_len_append_jit = jax.jit(
                _append1_kernel, donate_argnums=(0,)
            )

    def _intern(self, column: str, value: Optional[str]) -> int:
        if value is None:
            return -1
        table = self._codes[column]
        code = table.get(value)
        if code is None:
            code = len(table)
            table[value] = code
        return code

    def _append_columns(self, metadata: Sequence[Dict[str, Any]]) -> None:
        n = len(metadata)
        start = self._count
        for name, col in self._cols.items():
            if col.shape[0] < start + n:
                grown = np.full(
                    (max(start + n, 2 * max(1, col.shape[0])),), -1, np.int32
                )
                grown[: col.shape[0]] = col
                self._cols[name] = grown
        if self._deleted.shape[0] < start + n:
            grown_d = np.zeros(
                (max(start + n, 2 * max(1, self._deleted.shape[0])),), bool
            )
            grown_d[: self._deleted.shape[0]] = self._deleted
            self._deleted = grown_d
        for i, md in enumerate(metadata):
            self._cols["patient_id"][start + i] = self._intern(
                "patient_id", md.get("patient_id")
            )
            self._cols["doc_type"][start + i] = self._intern(
                "doc_type", md.get("doc_type")
            )
            self._cols["doc_id"][start + i] = self._intern(
                "doc_id", md.get("doc_id")
            )
            self._cols["doc_date"][start + i] = _date_code(md.get("doc_date"))
            if md.get("deleted"):  # restore path: tombstones persist
                self._deleted[start + i] = True
                self._n_deleted += 1

    # ---- capacity management -------------------------------------------------

    def _round_capacity(self, n: int) -> int:
        """Round up to a multiple of 128*n_shards (MXU sublane + even shards)."""
        quantum = 128 * self._n_shards
        return max(quantum, round_up(n, quantum))

    def _place_rows(self, arr: jax.Array) -> jax.Array:
        """Shard a [capacity, ...] array's rows over the model axis (no-op
        without a mesh) — the one placement rule for the vector buffer and
        its token sidecar, so the two can never drift apart."""
        if self.mesh is None:
            return arr
        return jax.device_put(arr, self.mesh.row_sharded)

    def _alloc(self, capacity: int) -> jax.Array:
        return self._place_rows(jnp.zeros((capacity, self.cfg.dim), self._dtype))

    def _grow_to(self, needed: int) -> None:
        new_cap = self._capacity
        while new_cap < needed:
            new_cap *= 2
        if new_cap == self._capacity:
            return
        log.info("store grow %d -> %d rows", self._capacity, new_cap)
        self._capacity = new_cap
        buf = np.zeros((new_cap, self.cfg.dim), np.float32)
        buf[: self._count] = self._host[: self._count]
        self._dev = self._place_rows(jnp.asarray(buf, self._dtype))
        if self.cfg.token_width:
            self._upload_tok_locked()

    def _upload_tok_locked(self) -> None:
        """Re-upload the sidecar device arrays at the current capacity from
        the host master copy (capacity change or compaction)."""
        W = self.cfg.token_width
        tok = np.zeros((self._capacity, W), np.int32)
        tok[: self._count] = self._tok_host[: self._count]
        tl = np.zeros((self._capacity,), np.int32)
        tl[: self._count] = self._tok_len_host[: self._count]
        self._tok_dev = self._place_rows(jnp.asarray(tok))
        self._tok_len_dev = self._place_rows(jnp.asarray(tl))

    # ---- public API ----------------------------------------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def deleted_count(self) -> int:
        """Tombstoned rows still occupying buffer slots (0 after
        ``compact_deleted``)."""
        return self._n_deleted

    @property
    def compactions(self) -> int:
        """How many times rows have been renumbered (``compact_deleted``
        erasures).  Captured at tier build and re-checked before any
        host-row re-rank: stale row ids must never index the compacted
        buffer."""
        with self._lock:
            return self._n_compactions

    @property
    def version(self) -> int:
        return self._version

    @property
    def dim(self) -> int:
        return self.cfg.dim

    def register_index_sink(self, sink: Any) -> None:
        """Register a secondary index consumer (protocol: ``on_add(row_ids,
        metadata)``, ``on_delete(row_ids)``, ``on_compact(keep_mask)``).
        One seam, every mutation path: the pipeline's journal-replayed
        ingest lands in :meth:`add`, so a registered sink needs no
        replay-awareness of its own.

        Registration is order-independent: rows already committed (e.g.
        a snapshot restore that ran before the sink existed) are
        back-filled through ``on_add`` immediately, tombstones included
        (the metadata row carries ``deleted`` — the sink decides)."""
        with self._lock:
            self._index_sinks.append(sink)
            if self._count:
                try:
                    sink.on_add(
                        list(range(self._count)), self._meta[: self._count]
                    )
                except Exception:
                    DEFAULT_REGISTRY.counter("index_sink_errors").inc()
                    log.exception("index sink %s backfill failed", sink)

    def _notify_sinks(self, method: str, *args) -> None:
        """Best-effort fan-out (called with the store lock held, after
        the dense mutation committed): a broken sink must not take dense
        ingest down with it, but it fails LOUDLY — the counter feeds the
        replay-convergence witness."""
        for sink in self._index_sinks:
            try:
                getattr(sink, method)(*args)
            except Exception:
                DEFAULT_REGISTRY.counter("index_sink_errors").inc()
                log.exception("index sink %s.%s failed", sink, method)

    def add(
        self,
        vectors: np.ndarray,
        metadata: Sequence[Dict[str, Any]],
        token_rows: Optional[np.ndarray] = None,
        token_lens: Optional[np.ndarray] = None,
    ) -> List[int]:
        """Append normalized vectors + metadata rows; returns global row ids.

        Visible to searches immediately (device-side append — the reference
        required a service restart, ``llm-qa/main.py:35``).

        ``token_rows``/``token_lens``: per-row generator-token ids for the
        sidecar (``cfg.token_width``); rows longer than the width are
        truncated, absent rows stay empty (the fused RAG path then renders
        that chunk as zero tokens).
        """
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.cfg.dim:
            raise ValueError(f"expected [n, {self.cfg.dim}] vectors, got {vectors.shape}")
        if len(vectors) != len(metadata):
            raise ValueError("vectors/metadata length mismatch")
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-9)

        with self._lock, span("store_add", DEFAULT_REGISTRY):
            start = self._count
            n = len(vectors)
            if self._host.shape[0] < start + n:
                grow = max(start + n, 2 * max(1, self._host.shape[0]))
                host = np.zeros((grow, self.cfg.dim), np.float32)
                host[:start] = self._host[:start]
                self._host = host
            self._host[start : start + n] = vectors
            # pad the appended block to a 64-row bucket so repeated adds of
            # varying sizes reuse a handful of compiled programs; the padding
            # lands beyond count (zeros over zeros) and capacity is grown to
            # keep the padded write in bounds
            n_pad = round_up(n, 64)

            def _append_on_lane():
                """Device phase (spine work item; submitter holds the
                store lock while blocked — the closure acquires
                nothing): capacity growth, the donated buffer append,
                and the token-sidecar append.  Returns the written
                device arrays so strict mode syncs every program this
                item issued before the lane frees."""
                self._grow_to(start + n_pad)
                rows = np.zeros((n_pad, self.cfg.dim), np.float32)
                rows[:n] = vectors
                self._dev = self._append_jit(
                    self._dev, jnp.asarray(rows, self._dtype), start
                )
                if self.cfg.token_width:
                    self._append_tokens_locked(
                        start, n, n_pad, token_rows, token_lens
                    )
                    return self._dev, self._tok_dev, self._tok_len_dev
                return self._dev

            spine_run("store_add", _append_on_lane)
            self._meta.extend(dict(m) for m in metadata)
            self._append_columns(metadata)
            self._count = start + n
            self._version += 1
            row_ids = list(range(start, start + n))
            self._notify_sinks("on_add", row_ids, metadata)
            return row_ids

    def _append_tokens_locked(
        self, start, n, n_pad, token_rows, token_lens
    ) -> None:
        W = self.cfg.token_width
        block = np.zeros((n_pad, W), np.int32)
        lens = np.zeros((n_pad,), np.int32)
        if token_rows is not None:
            token_rows = np.asarray(token_rows, np.int32)
            w = min(W, token_rows.shape[1])
            block[:n, :w] = token_rows[:, :w]
            if token_lens is None:
                token_lens = (token_rows != 0).sum(axis=1)
            lens[:n] = np.minimum(np.asarray(token_lens, np.int32), W)
        if self._tok_host.shape[0] < start + n:
            grow = max(start + n, 2 * max(1, self._tok_host.shape[0]))
            th = np.zeros((grow, W), np.int32)
            th[: self._tok_host.shape[0]] = self._tok_host
            tl = np.zeros((grow,), np.int32)
            tl[: self._tok_len_host.shape[0]] = self._tok_len_host
            self._tok_host, self._tok_len_host = th, tl
        self._tok_host[start : start + n] = block[:n]
        self._tok_len_host[start : start + n] = lens[:n]
        self._tok_dev = self._tok_append_jit(
            self._tok_dev, jnp.asarray(block), start
        )
        self._tok_len_dev = self._tok_len_append_jit(
            self._tok_len_dev, jnp.asarray(lens), start
        )

    def token_sidecar(self):
        """(tokens [capacity, W] int32, lengths [capacity] int32) device
        arrays, or None when the sidecar is disabled.  The PAIR is
        snapshotted under the store lock: each reference store is atomic
        under the GIL, but reading them back-to-back lock-free could
        pair a post-append token table with a pre-append length vector
        (guarded-state, PR 8) — the fused program would then score one
        phantom row."""
        if not self.cfg.token_width:
            return None
        with self._lock:
            return self._tok_dev, self._tok_len_dev

    def _get_search_fn(self, q: int, k: int, masked: bool) -> Callable:
        key = (self._capacity, q, k, masked)
        fn = self._search_fns.get(key)
        if fn is not None:
            return fn
        if self.mesh is not None and self._n_shards > 1:
            kernel = functools.partial(
                _search_kernel, k=k, axis=self.mesh.model_axis
            )
            in_specs = [
                P(self.mesh.model_axis, None),  # vectors row-sharded
                P(),  # queries replicated
                P(),  # count
            ]
            if masked:
                in_specs.append(P())  # filter mask replicated
                wrapped = kernel
            else:
                def wrapped(vectors, queries, count):
                    return kernel(vectors, queries, count, None)

            fn = jax.jit(
                shard_map(
                    wrapped,
                    mesh=self.mesh.mesh,
                    in_specs=tuple(in_specs),
                    out_specs=(P(), P()),
                    check_vma=False,
                )
            )
        else:
            single = functools.partial(_search_single, k=k)
            if masked:
                fn = jax.jit(single)
            else:
                fn = jax.jit(lambda v, q, c: single(v, q, c, None))
        self._search_fns[key] = fn
        return fn

    def _filter_mask_locked(self, filters: Dict[str, Any]) -> np.ndarray:
        """Vectorized [capacity] bool mask from a columnar filter spec
        (keys: patient_id, doc_type, date_from, date_to).  Rows without a
        date are excluded when a date bound is given — the reference's
        patient-snippet semantics (``qa.py`` belongs())."""
        unknown = set(filters) - set(self._FILTER_KEYS)
        if unknown:
            raise ValueError(f"unknown filter keys: {sorted(unknown)}")
        count, capacity = self._count, self._capacity
        mask = np.zeros((capacity,), bool)
        live = np.ones((count,), bool)
        for column in ("patient_id", "doc_type"):
            value = filters.get(column)
            if value is not None:
                # unseen value interns to no row: code -2 matches nothing
                code = self._codes[column].get(value, -2)
                live &= self._cols[column][:count] == code
        dates = self._cols["doc_date"][:count]
        for bound in ("date_from", "date_to"):
            value = filters.get(bound)
            if not value:  # None OR '' — unfilled form fields mean no bound
                continue
            code = _date_code(value)
            if code < 0:
                # silent mis-parses would alter medical-record query
                # semantics (a dropped lower bound over-returns; a poisoned
                # upper bound returns nothing) — reject loudly instead
                raise ValueError(
                    f"{bound}={value!r} is not an ISO date (YYYY-MM-DD)"
                )
            if bound == "date_from":
                live &= dates >= code
            else:
                live &= dates <= code
        if filters.get("date_from") or filters.get("date_to"):
            live &= dates >= 0  # undated rows excluded when bounds given
        if self._n_deleted:
            live &= ~self._deleted[:count]
        mask[:count] = live
        return mask

    def _live_mask_locked(self) -> Optional[np.ndarray]:
        """[capacity] live mask, or None when nothing is deleted — the
        zero-tombstone path keeps unfiltered searches mask-free (a mask
        upload costs a host->device transfer per query batch)."""
        if not self._n_deleted:
            return None
        mask = np.zeros((self._capacity,), bool)
        mask[: self._count] = ~self._deleted[: self._count]
        return mask

    def _compose_live_locked(
        self, mask: Optional[np.ndarray], already_live: bool
    ) -> Optional[np.ndarray]:
        """Fold the tombstone mask into an (optional) filter mask — the ONE
        place the live-rows invariant lives, so every search surface
        composes it identically.  ``already_live``: the mask came from
        ``_filter_mask_locked`` (which ANDs tombstones itself)."""
        if already_live or not self._n_deleted:
            return mask
        live = self._live_mask_locked()
        return live if mask is None else (mask & live)

    def delete_docs(self, doc_ids: Sequence[str]) -> int:
        """Tombstone every chunk of the given documents: rows vanish from
        all searches/listings immediately; vector bytes remain in HBM and
        snapshots until ``compact_deleted``.  Returns rows tombstoned."""
        with self._lock:
            count = self._count
            if count == 0:
                return 0
            codes = [
                self._codes["doc_id"].get(d)
                for d in doc_ids
                if self._codes["doc_id"].get(d) is not None
            ]
            if not codes:
                return 0
            hit = np.isin(self._cols["doc_id"][:count], codes)
            hit &= ~self._deleted[:count]
            n = int(hit.sum())
            if n == 0:
                return 0
            self._deleted[:count] |= hit
            self._n_deleted += n
            for i in np.nonzero(hit)[0]:
                self._meta[int(i)]["deleted"] = True  # persists via snapshot
            self._version += 1
            self._notify_sinks(
                "on_delete", [int(i) for i in np.nonzero(hit)[0]]
            )
            log.info("tombstoned %d rows across %d docs", n, len(codes))
            return n

    def compact_deleted(self) -> int:
        """Physically remove tombstoned rows (real erasure, not a mask):
        rewrites the host copy, columns, and the device buffer.  Row ids
        change — any derived index (IVF/tiered) must rebuild from the new
        state.  Returns rows removed."""
        with self._lock:
            count = self._count
            if not self._n_deleted:
                return 0
            keep = ~self._deleted[:count]
            removed = count - int(keep.sum())
            self._host = self._host[:count][keep].copy()
            if self.cfg.token_width:
                self._tok_host = self._tok_host[:count][keep].copy()
                self._tok_len_host = self._tok_len_host[:count][keep].copy()
            self._meta = [
                md for md, k in zip(self._meta, keep) if k
            ]
            self._count = int(keep.sum())
            # rebuild interned columns from scratch (codes for deleted-only
            # values are dropped with them)
            self._codes = {"patient_id": {}, "doc_type": {}, "doc_id": {}}
            self._cols = {
                "patient_id": np.zeros((0,), np.int32),
                "doc_type": np.zeros((0,), np.int32),
                "doc_id": np.zeros((0,), np.int32),
                "doc_date": np.zeros((0,), np.int32),
            }
            self._deleted = np.zeros((0,), bool)
            self._n_deleted = 0
            saved_count = self._count
            self._count = 0
            self._append_columns(self._meta)
            self._count = saved_count
            # fresh device buffer from the compacted host copy
            n_pad = round_up(max(self._count, 1), 64)
            self._capacity = self._round_capacity(max(n_pad, 128))

            def _reupload_on_lane():
                buf = np.zeros((self._capacity, self.cfg.dim), np.float32)
                buf[: self._count] = self._host[: self._count]
                self._dev = self._place_rows(jnp.asarray(buf, self._dtype))
                if self.cfg.token_width:
                    self._upload_tok_locked()
                    return self._dev, self._tok_dev, self._tok_len_dev
                return self._dev

            spine_run("store_add", _reupload_on_lane)
            if self._count == 0:  # keep a 1-row pad so slicing stays valid
                self._host = np.zeros((1, self.cfg.dim), np.float32)
            self._n_compactions += 1
            self._version += 1
            self._notify_sinks("on_compact", keep.copy())
            log.info("compacted %d deleted rows; %d remain", removed, self._count)
            return removed

    def metadata_select(
        self,
        limit: Optional[int] = None,
        **filters: Any,
    ) -> List[Dict[str, Any]]:
        """Filtered metadata listing (row order) via the columnar mask —
        the non-semantic patient-snippets path, O(matches) not O(corpus)."""
        with self._lock:
            count = self._count
            if count == 0:
                return []
            idx = np.nonzero(self._filter_mask_locked(filters)[:count])[0]
            if limit is not None:
                idx = idx[:limit]
            return [self._meta[int(i)] for i in idx]

    def search(
        self,
        queries: np.ndarray,
        k: Optional[int] = None,
        where: Optional[Callable[[Dict[str, Any]], bool]] = None,
        filters: Optional[Dict[str, Any]] = None,
    ) -> List[List[SearchResult]]:
        """Exact top-k over the live buffer.

        ``filters``: columnar metadata filter (patient_id / doc_type /
        date_from / date_to) built into the device mask with vectorized
        compares — the fast path.  ``where``: arbitrary host predicate,
        O(corpus) Python — escape hatch only; both compose with AND.
        """
        k = k or self.cfg.default_k
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        # Dispatch under the lock: add() donates the device buffer, so the
        # buffer reference must not be used for a new dispatch after an add
        # replaced it.  The enqueued computation holds its own runtime
        # reference, so only the dispatch (not the result fetch) needs the
        # lock.  _meta is append-only, so rows < count are stable to read
        # outside the lock.
        with self._lock:
            count = self._count
            capacity = self._capacity
            if count == 0:
                return [[] for _ in queries]
            k_eff = min(k, count)
            mask = None
            if filters:
                mask = self._filter_mask_locked(filters)
            if where is not None:
                host = np.zeros((capacity,), bool)
                for i in range(count):
                    host[i] = bool(where(self._meta[i]))
                mask = host if mask is None else (mask & host)
            mask = self._compose_live_locked(mask, already_live=bool(filters))

            def _search_on_lane():
                """Dispatch phase (spine work item; submitter holds the
                lock while blocked): program build, query upload, and
                the async enqueue against the current buffer."""
                fn = self._get_search_fn(
                    len(qn), k_eff, masked=mask is not None
                )
                args = [
                    self._dev, jnp.asarray(qn, self._dtype), jnp.int32(count)
                ]
                if mask is not None:
                    args.append(jnp.asarray(mask))
                return fn(*args)

            with span("store_search", DEFAULT_REGISTRY):
                vals_dev, ids_dev = spine_run("store_search", _search_on_lane)
        # the fetch runs OUTSIDE the lock (the enqueued computation holds
        # its own buffer reference) but still on a spine lane: blocking
        # on the device result is device time, and bounded like any other
        vals, ids = spine_run(
            "store_search_fetch",
            lambda: (np.asarray(vals_dev), np.asarray(ids_dev)),
        )
        return self.assemble_results(vals, ids)

    def shadow_search(
        self, queries: np.ndarray, k: int, count_cap: Optional[int] = None
    ) -> List[List[SearchResult]]:
        """Exact tombstone-masked top-k as a BACKGROUND probe — the
        retrieval observatory's ground-truth scan (``obs/retrieval_
        observatory.py``).  Identical ranking semantics to :meth:`search`
        (same kernels, same live-mask composition, no filters), but the
        device work rides the spine's background ``probe`` stream under
        the dedicated ``retrieve_shadow`` stage: capped at n_lanes-1, it
        can never occupy the last serving lane, and ``dispatch_*``
        telemetry attributes exactly what shadow sampling costs.

        ``count_cap`` bounds the scanned rows to the corpus size the
        SERVED query saw: a shadow that lags a concurrent ingest must
        not count rows the tier could not have returned as misses."""
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None]
        qn = queries / np.maximum(
            np.linalg.norm(queries, axis=1, keepdims=True), 1e-9
        )
        # dispatch under the lock / fetch outside: the same donation
        # discipline as search() (see the comment there)
        with self._lock:
            count = self._count
            if count_cap is not None:
                count = min(count, int(count_cap))
            if count == 0:
                return [[] for _ in queries]
            k_eff = min(k, count)
            mask = self._compose_live_locked(None, already_live=False)

            def _shadow_on_lane():
                """Dispatch phase (spine work item; submitter holds the
                lock while blocked — the closure acquires nothing)."""
                fn = self._get_search_fn(
                    len(qn), k_eff, masked=mask is not None
                )
                args = [
                    self._dev, jnp.asarray(qn, self._dtype), jnp.int32(count)
                ]
                if mask is not None:
                    args.append(jnp.asarray(mask))
                return fn(*args)

            vals_dev, ids_dev = spine_run(
                "retrieve_shadow", _shadow_on_lane, stream="probe"
            )
        vals, ids = spine_run(
            "retrieve_shadow",
            lambda: (np.asarray(vals_dev), np.asarray(ids_dev)),
            stream="probe",
        )
        return self.assemble_results(vals, ids)

    def assemble_results(
        self, vals: np.ndarray, ids: np.ndarray
    ) -> List[List[SearchResult]]:
        """Host-side (score, row-id) -> SearchResult rows with metadata;
        shared by ``search`` and the fused text-query path
        (``engines/retrieve.py``).  ``_meta`` is append-only, so reading it
        lock-free for rows the device has already scored is safe."""
        out: List[List[SearchResult]] = []
        for qi in range(len(vals)):
            row: List[SearchResult] = []
            for score, rid in zip(vals[qi], ids[qi]):
                if score <= NEG_INF / 2:
                    continue  # filtered / dead row
                row.append(
                    SearchResult(float(score), int(rid), self._meta[int(rid)])
                )
            out.append(row)
        return out

    def metadata_rows(self) -> List[Dict[str, Any]]:
        """Stable copy of the live metadata (row order == insertion order) —
        backs non-semantic listings like patient-snippet retrieval without a
        device round-trip."""
        with self._lock:
            return list(self._meta[: self._count])

    def host_rows(self, ids: np.ndarray) -> np.ndarray:
        """L2-normalized f32 vectors for the given row ids, from the host
        master copy — the full-precision view the tiered index's exact
        re-rank scores against (``index/tiered.py:_rerank_bulk``; the
        int8 tier's quantization error is confined to candidate
        selection this way).  Lock-free by the same append-only argument
        as ``assemble_results``: rows the caller already holds ids for
        are immutable, and ``_host`` reallocation publishes a whole new
        array reference (atomic under the GIL), never a torn row."""
        return self._host[np.asarray(ids, np.int64)]  # docqa-lint: disable=guarded-state

    def vectors_snapshot(
        self, start: int = 0
    ) -> Tuple[np.ndarray, List[Dict[str, Any]]]:
        """Consistent (vectors, metadata) pair for rows [start, count) under
        one lock acquisition — the safe input for offline rebuilds (IVF) and
        tail slices (TieredIndex) while add() runs concurrently."""
        with self._lock:
            return self._host[start : self._count].copy(), list(
                self._meta[start : self._count]
            )

    # ---- versioned snapshot (checkpoint/resume parity, SURVEY §5) -----------

    def snapshot(self, directory: str, keep_previous: bool = True) -> str:
        """Atomic versioned publish: vectors + metadata + manifest.

        Write-temp + rename — a reader never sees a half-written index
        (the reference's save had no such guarantee, ``indexer.py:26-30``).

        ``keep_previous=False`` prunes every superseded snapshot instead of
        retaining one rollback predecessor — required after an erasure
        compaction, where the predecessor still holds the erased vectors
        and de-identified text on disk."""
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            count, version = self._count, self._version
            vectors = self._host[:count].copy()
            meta = list(self._meta)
            tokens = token_lens = None
            if self.cfg.token_width:
                tokens = self._tok_host[:count].copy()
                token_lens = self._tok_len_host[:count].copy()
        base = os.path.join(directory, f"index_v{version}")
        tmp = tempfile.mkdtemp(dir=directory)
        # checksummed native codec (C++ DNS1 shard, crc32-verified mmap read)
        # when the library is available; .npy otherwise
        vec_path = native.write_vectors(os.path.join(tmp, "vectors"), vectors)
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(meta, f)
        manifest = {
            "version": version,
            "count": count,
            "dim": self.cfg.dim,
            "vectors": os.path.basename(vec_path),
        }
        if tokens is not None:
            np.save(os.path.join(tmp, "tokens.npy"), tokens)
            np.save(os.path.join(tmp, "token_lens.npy"), token_lens)
            manifest["tokens"] = "tokens.npy"
            manifest["token_width"] = self.cfg.token_width
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        import shutil

        if os.path.exists(base):
            # Same version number does NOT imply same content: after a
            # failed restore the runtime starts a fresh store at version 0
            # in a work dir that still holds old index_vN dirs — publishing
            # must REPLACE the stale dir, or data ingested since the failure
            # would be silently dropped while LATEST points at old vectors.
            shutil.rmtree(base)
        os.replace(tmp, base)
        latest = os.path.join(directory, "LATEST")
        with open(latest + ".tmp", "w") as f:
            f.write(f"index_v{version}")
        os.replace(latest + ".tmp", latest)
        # prune superseded snapshots (keep the published one + its
        # predecessor as a rollback safety net)
        versions = sorted(
            (
                int(d.split("index_v", 1)[1])
                for d in os.listdir(directory)
                if d.startswith("index_v")
                and d.split("index_v", 1)[1].isdigit()
            ),
            reverse=True,
        )
        for old in versions[1 if not keep_previous else 2:]:
            shutil.rmtree(
                os.path.join(directory, f"index_v{old}"), ignore_errors=True
            )
        return base

    @classmethod
    def restore(
        cls,
        directory: str,
        cfg: StoreConfig,
        mesh: Optional[MeshContext] = None,
    ) -> "VectorStore":
        with open(os.path.join(directory, "LATEST")) as f:
            base = os.path.join(directory, f.read().strip())
        with open(os.path.join(base, "manifest.json")) as f:
            manifest = json.load(f)
        vectors = native.read_vectors(
            os.path.join(base, manifest.get("vectors", "vectors.npy"))
        )
        with open(os.path.join(base, "metadata.json")) as f:
            meta = json.load(f)
        store = cls(cfg, mesh=mesh)
        tokens = token_lens = None
        if cfg.token_width and manifest.get("tokens"):
            tokens = np.load(os.path.join(base, manifest["tokens"]))
            token_lens = np.load(os.path.join(base, "token_lens.npy"))
        if len(vectors):
            store.add(vectors, meta, token_rows=tokens, token_lens=token_lens)
        store._version = manifest["version"]
        return store
