from docqa_tpu.index.store import SearchResult, VectorStore
from docqa_tpu.index.tiered import TieredIndex

__all__ = ["VectorStore", "SearchResult", "TieredIndex"]
