from docqa_tpu.index.store import SearchResult, VectorStore

__all__ = ["VectorStore", "SearchResult"]
