"""Attention ops: XLA reference path + Pallas TPU flash kernel.

Replaces what the reference outsourced entirely (attention lived inside
Ollama/llama.cpp and torch sentence-transformers — ``llm-qa/main.py:66-69``,
``semantic-indexer/indexer.py:21``).  Design per SURVEY §5 "long-context":
the kernel is blockwise over the KV axis with online softmax, so the sequence
axis can shard across devices — ``parallel/ring_attention.py`` reuses the
same blockwise accumulation over an ICI ring.

Layouts:
  q        [batch, q_len, num_q_heads, head_dim]
  k, v     [batch, kv_len, num_kv_heads, head_dim]   (GQA: q_heads % kv_heads == 0)
  lengths  [batch] int32 — valid KV prefix per example (padding mask)

The dispatcher :func:`attention` picks the Pallas kernel on TPU and the pure
XLA path elsewhere (CPU tests run the kernel in interpret mode explicitly).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# --------------------------------------------------------------------------
# Reference XLA implementation (also the CPU path and the golden model)
# --------------------------------------------------------------------------

def attention_reference(
    q,
    k,
    v,
    *,
    causal: bool = False,
    lengths: Optional[jax.Array] = None,
    q_offset: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    scale: Optional[float] = None,
):
    """Plain XLA attention.  f32 softmax, bf16 matmuls via preferred type.

    The upcast-before-math recipe below (``astype(float32)`` on q/k/v,
    softmax over f32 scores) is the dtype contract the ``dtype-flow``
    lint rule enforces tree-wide (docs/STATIC_ANALYSIS.md): a bf16
    operand reaching an einsum/softmax without this upcast is a red
    build, not a convention.

    ``q_offset`` [batch]: absolute position of q[:, 0] (decode steps where
    q_len << kv_len).  Defaults to aligning the *ends* of q and kv when
    causal (standard prefill/decode convention).
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    groups = hq // hkv
    scale = scale if scale is not None else d ** -0.5
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal=True (bidirectional local attention is not implemented)")

    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if groups > 1:
        kf = jnp.repeat(kf, groups, axis=2)
        vf = jnp.repeat(vf, groups, axis=2)

    # [b, h, sq, skv]
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf)

    kv_pos = jnp.arange(skv)[None, None, None, :]
    mask = jnp.ones((b, 1, sq, skv), dtype=bool)
    if lengths is not None:
        mask &= kv_pos < lengths[:, None, None, None]
    if causal:
        if q_offset is None:
            q_abs = jnp.arange(sq)[None, :] + (
                (lengths[:, None] if lengths is not None else skv) - sq
            )
        else:
            q_abs = jnp.arange(sq)[None, :] + q_offset[:, None]
        q_abs = q_abs[:, None, :, None]  # [b,1,sq,1]
        mask &= kv_pos <= q_abs
        if sliding_window is not None:
            mask &= kv_pos > q_abs - sliding_window
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # a row with no valid kv position (can only happen on padding rows)
    # outputs zeros, matching the flash kernel
    probs = jnp.where(jnp.any(mask, axis=-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vf)
    return out.astype(q.dtype)


# --------------------------------------------------------------------------
# Ragged / paged attention (block-table KV; arXiv 2604.15464 contract)
# --------------------------------------------------------------------------

# Sequence starts inside a packed ragged-prefill batch are aligned to this
# many rows.  The alignment exists for EXACTNESS, not speed: XLA's softmax
# reductions (strided SIMD accumulators, power-of-two trees, or sequential
# sums) all produce bitwise-identical partial sums when the non-zero
# segment of a masked row starts at a multiple of the reduction's lane
# width — so a prompt prefilled at offset 128k yields the SAME tokens as
# the solo engine's offset-0 prefill, which is the serve-vs-solo
# token-equality invariant every batcher test pins.  A production Pallas
# RPA kernel packs densely and masks in-kernel instead; this is the XLA
# reference path's price for bitwise parity.
RAGGED_ALIGN = 128


def ragged_prefill_attention(q, k, v, seg_ids, positions, *,
                             sliding_window=None, scale=None,
                             k_pool=None, v_pool=None, block_tables=None,
                             prefix_lens=None, n_prefix_rows=0,
                             block_size=None):
    """Self-attention over a PACKED batch of variable-length prompts —
    the prefill half of Ragged Paged Attention, XLA reference path.

    q, k, v   [T, heads, d] — ONE flat token axis; each prompt occupies a
              contiguous run of rows (starts aligned to RAGGED_ALIGN)
    seg_ids   [T] int32 — sequence id per token; negative = padding row
    positions [T] int32 — position of each token within its own sequence

    A token attends only within its own segment, causally by position
    (plus the optional sliding window).  f32 softmax, same dtype contract
    as :func:`attention_reference`; padding rows output zeros.  There is
    no shape family here: any mix of prompt lengths that fits T shares
    one compiled program.

    Computed in RAGGED_ALIGN-row query blocks (a ``lax.map`` over the
    packed axis) so the score transient is O(heads x ALIGN x T), never
    the full O(heads x T x T) — at a 4096-token budget and 7B head
    count the quadratic form would be ~2 GB of f32 per layer, which the
    bucketed prefill this replaced never materialized.  Per-row numerics
    are IDENTICAL to the single-shot form (each row still reduces over
    the same [T] axis), so the block split cannot perturb greedy
    outputs.  The Pallas RPA kernel that also skips cross-segment
    blocks entirely is the TPU follow-up.

    WARM mode (``n_prefix_rows > 0``, the copy-on-write prefix-cache
    path, docqa-prefix): each segment may additionally attend a CACHED
    prompt prefix read from the paged KV pool through its block table.
    ``positions`` then start at the segment's prefix length, and the key
    axis becomes ``[n_prefix_rows ; T]`` — per query block, the owning
    lane's first ``prefix_lens[lane]`` pool rows are gathered in
    position order ahead of the packed keys.  Because a shared prefix is
    RAGGED_ALIGN-aligned (engines/paged.py ``share_alignment``), every
    valid key keeps its position residue mod the alignment, and the
    pool's stored K/V are the very bf16 values a cold prefill would
    compute in-flight — so the softmax reduction trees, and therefore
    the sampled tokens, are bitwise identical to prefilling the whole
    prompt cold.  ``n_prefix_rows`` is a static shape (the sequence
    capacity); unused rows are masked.  Segment starts must be aligned
    (each query block then belongs to exactly one segment, so one block
    table row serves the whole block).
    """
    t, hq, d = q.shape
    _, hkv, _ = k.shape
    groups = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if groups > 1:
        kf = jnp.repeat(kf, groups, axis=1)
        vf = jnp.repeat(vf, groups, axis=1)

    valid = seg_ids >= 0
    # n_prefix_rows is a STATIC host int (the batcher's seq capacity) —
    # never a tracer; no cast so the jit-purity host-sync rule stays
    # meaningful here
    warm = n_prefix_rows > 0
    if warm:
        if t % RAGGED_ALIGN:
            raise ValueError(
                "warm ragged prefill needs a RAGGED_ALIGN-multiple "
                f"packed axis (got T={t})"
            )
        n_blocks = k_pool.shape[0] // block_size
        pool_rows = k_pool.shape[0]
        pfx_cols = jnp.arange(n_prefix_rows)

    def attend_rows(row_idx):
        """One query block: rows ``row_idx`` [bq] against all keys."""
        qb = qf[row_idx]  # [bq, hq, d]
        seg_q = seg_ids[row_idx]
        pos_q = positions[row_idx]
        scores = jnp.einsum("qhd,khd->hqk", qb, kf)  # [hq, bq, T]
        mask = (
            (seg_q[:, None] == seg_ids[None, :])
            & (valid[row_idx][:, None] & valid[None, :])
            & (positions[None, :] <= pos_q[:, None])
        )
        if sliding_window is not None:
            mask &= positions[None, :] > pos_q[:, None] - sliding_window
        mask = mask[None, :, :]  # [1, bq, T]
        if not warm:
            scores = jnp.where(mask, scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            # fully-masked rows (padding) output zeros, like the dense
            # path
            probs = jnp.where(
                jnp.any(mask, axis=-1, keepdims=True), probs, 0.0
            )
            return jnp.einsum("hqk,khd->qhd", probs, vf)  # [bq, hq, d]
        # ---- warm: prepend the lane's cached prefix K/V (pool rows in
        # position order) to the key axis.  Aligned segment starts mean
        # this whole query block belongs to ONE lane (or is padding).
        lane = jnp.max(seg_q)  # -1 when the block is all padding
        lane_c = jnp.maximum(lane, 0)
        row_tab = jax.lax.dynamic_index_in_dim(
            block_tables, lane_c, axis=0, keepdims=False
        )  # [NB]
        blk = row_tab[pfx_cols // block_size]
        rows = jnp.minimum(
            blk * block_size + pfx_cols % block_size, pool_rows - 1
        )
        kp = k_pool[rows].astype(jnp.float32)  # [PFX, hkv, d]
        vp = v_pool[rows].astype(jnp.float32)
        if groups > 1:
            kp = jnp.repeat(kp, groups, axis=1)
            vp = jnp.repeat(vp, groups, axis=1)
        plen = jax.lax.dynamic_index_in_dim(
            prefix_lens, lane_c, axis=0, keepdims=False
        )
        scores_p = jnp.einsum("qhd,khd->hqk", qb, kp)  # [hq, bq, PFX]
        mask_p = (
            (lane >= 0)
            & valid[row_idx][:, None]
            & (pfx_cols[None, :] < plen)
            & (blk[None, :] < n_blocks)
            & (pfx_cols[None, :] <= pos_q[:, None])
        )
        if sliding_window is not None:
            mask_p &= pfx_cols[None, :] > pos_q[:, None] - sliding_window
        mask_p = mask_p[None, :, :]  # [1, bq, PFX]
        # ONE flat softmax over [prefix ; packed] in position order:
        # masked rows contribute exact zeros, and alignment keeps every
        # valid key's reduction-tile residue — bitwise equal to cold
        full_scores = jnp.concatenate([scores_p, scores], axis=-1)
        full_mask = jnp.concatenate([mask_p, mask], axis=-1)
        full_scores = jnp.where(full_mask, full_scores, NEG_INF)
        probs = jax.nn.softmax(full_scores, axis=-1)
        probs = jnp.where(
            jnp.any(full_mask, axis=-1, keepdims=True), probs, 0.0
        )
        vcat = jnp.concatenate([vp, vf], axis=0)  # [PFX + T, hq, d]
        return jnp.einsum("hqk,khd->qhd", probs, vcat)

    if t % RAGGED_ALIGN or t <= RAGGED_ALIGN:
        out = attend_rows(jnp.arange(t))
    else:
        blocks = jnp.arange(t).reshape(t // RAGGED_ALIGN, RAGGED_ALIGN)
        out = jax.lax.map(attend_rows, blocks).reshape(t, hq, d)
    return out.astype(q.dtype)


def gather_paged_kv(pool, block_tables, block_size):
    """Gather a per-sequence contiguous KV view out of a flat block pool.

    pool         [P, kv_heads, d] — P = n_blocks * block_size flat rows
    block_tables [S, NB] int32 — block ids per sequence; ids >= n_blocks
                 are holes (unallocated tail), clamped and later masked
                 by the caller's ``lengths``

    Returns [S, NB * block_size, kv_heads, d]: row p of sequence s is
    that sequence's token-position p, exactly the layout a dense
    per-lane cache would have — so downstream attention reductions are
    bitwise identical to the contiguous-cache path.
    """
    S, nb = block_tables.shape
    L = nb * block_size
    P = pool.shape[0]
    cols = jnp.arange(L)
    blk = jnp.take(block_tables, cols // block_size, axis=1)  # [S, L]
    rows = jnp.minimum(blk * block_size + cols[None, :] % block_size, P - 1)
    return pool[rows]


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths, *,
                           block_size, q_offset=None, sliding_window=None,
                           scale=None, use_flash=False):
    """Decode-side attention through a block table (the decode half of
    Ragged Paged Attention).  XLA reference path: gather the pages into a
    per-sequence contiguous view, then run the standard masked kernel —
    a TPU Pallas kernel would stream pages without materializing the
    gather; this backs it the same way :func:`attention_reference` backs
    :func:`flash_attention`.

    q            [S, s, q_heads, d] (s = 1 plain step, K spec verify)
    k/v_pool     [P, kv_heads, d] flat block pool
    block_tables [S, NB] int32
    lengths      [S] valid kv length per sequence AFTER this step
    """
    k = gather_paged_kv(k_pool, block_tables, block_size)
    v = gather_paged_kv(v_pool, block_tables, block_size)
    attn_fn = flash_attention if use_flash else attention_reference
    return attn_fn(
        q, k, v, causal=True, lengths=lengths, q_offset=q_offset,
        sliding_window=sliding_window, scale=scale,
    )


# --------------------------------------------------------------------------
# Pallas flash kernel
# --------------------------------------------------------------------------

def _flash_kernel(
    # scalar prefetch
    lengths_ref,  # [b] int32 valid kv length
    qoff_ref,  # [b] int32 absolute position of q row 0
    # blocks
    q_ref,  # [1, bq, d]
    k_ref,  # [1, bkv, d]
    v_ref,  # [1, bkv, d]
    o_ref,  # [1, bq, d]
    # scratch
    acc_ref,  # [bq, d] f32
    m_ref,  # [bq, 128] f32 running max (lane-replicated)
    l_ref,  # [bq, 128] f32 running denom
    *,
    causal: bool,
    sliding_window: Optional[int],
    scale: float,
    block_kv: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_kv = pl.num_programs(2)

    # grid dim 0 is batch*q_heads; recover the batch index for scalars
    batch = pl.program_id(0) // (pl.num_programs(0) // lengths_ref.shape[0])
    kv_len = lengths_ref[batch]
    q_off = qoff_ref[batch]

    bq = q_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    kv_start = ki * block_kv
    q_rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 0)
    kv_cols = kv_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_kv), 1)
    q_abs = q_rows + q_off

    mask = kv_cols < kv_len
    if causal:
        mask &= kv_cols <= q_abs
        if sliding_window is not None:
            mask &= kv_cols > q_abs - sliding_window

    # Skip fully-masked blocks: past kv_len, beyond the causal frontier, or
    # entirely before the sliding window of every q row in this block.
    block_live = kv_start < kv_len
    if causal:
        q_abs_max = qi * bq + bq - 1 + q_off
        block_live &= kv_start <= q_abs_max
        if sliding_window is not None:
            q_abs_min = qi * bq + q_off
            block_live &= kv_start + block_kv > q_abs_min - sliding_window

    @pl.when(block_live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bkv]
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # explicit re-mask: in a fully-masked block m_new == NEG_INF and
        # exp(s - m_new) would be 1, not 0
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)  # [bq, bkv]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, d]
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_kv - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    lengths: Optional[jax.Array] = None,
    q_offset: Optional[jax.Array] = None,
    sliding_window: Optional[int] = None,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_kv: int = 512,
    interpret: bool = False,
):
    """Blockwise flash attention as a Pallas TPU kernel.

    Grid: (batch*q_heads, q_blocks, kv_blocks) — the kv axis is innermost so
    the online-softmax scratch carries across kv steps on one core.  GQA is
    handled by indexing the kv head as ``q_head // group``.
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if sliding_window is not None and not causal:
        raise ValueError("sliding_window requires causal=True (bidirectional local attention is not implemented)")
    groups = hq // hkv
    scale = scale if scale is not None else d ** -0.5

    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)

    # Pad seq lengths up to block multiples (static shapes; masked out).
    pq = (-sq) % block_q
    pkv = (-skv) % block_kv
    if pq:
        q = jnp.pad(q, ((0, 0), (0, pq), (0, 0), (0, 0)))
    if pkv:
        k = jnp.pad(k, ((0, 0), (0, pkv), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pkv), (0, 0), (0, 0)))
    sq_p, skv_p = sq + pq, skv + pkv

    if lengths is None:
        lengths = jnp.full((b,), skv, jnp.int32)
    if q_offset is None:
        q_offset = lengths - sq if causal else jnp.zeros((b,), jnp.int32)

    # [b, s, h, d] -> [b*h, s, d]
    qr = q.transpose(0, 2, 1, 3).reshape(b * hq, sq_p, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, skv_p, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, skv_p, d)

    grid = (b * hq, sq_p // block_q, skv_p // block_kv)

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        sliding_window=sliding_window,
        scale=scale,
        block_kv=block_kv,
    )

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            # index maps receive (grid..., *scalar_prefetch_refs)
            in_specs=[
                pl.BlockSpec(
                    (1, block_q, d), lambda h, qi, ki, *_: (h, qi, 0)
                ),
                pl.BlockSpec(
                    (1, block_kv, d),
                    lambda h, qi, ki, *_, groups=groups: (h // groups, ki, 0),
                ),
                pl.BlockSpec(
                    (1, block_kv, d),
                    lambda h, qi, ki, *_, groups=groups: (h // groups, ki, 0),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, d), lambda h, qi, ki, *_: (h, qi, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
                pltpu.VMEM((block_q, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b * hq, sq_p, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q_offset.astype(jnp.int32), qr, kr, vr)

    out = out.reshape(b, hq, sq_p, d).transpose(0, 2, 1, 3)
    return out[:, :sq]


# --------------------------------------------------------------------------
# Dispatcher
# --------------------------------------------------------------------------

_FLASH_ONLY_KWARGS = ("block_q", "block_kv", "interpret")


def attention(q, k, v, **kwargs):
    """Use the Pallas kernel on TPU, the XLA path elsewhere.

    Platform is resolved from the default backend (a host-side constant), not
    from the arrays — this function is called from inside ``jit`` where the
    inputs are tracers.
    """
    if jax.default_backend() == "tpu" and q.shape[-1] % 64 == 0:
        return flash_attention(q, k, v, **kwargs)
    for kw in _FLASH_ONLY_KWARGS:
        kwargs.pop(kw, None)
    return attention_reference(q, k, v, **kwargs)
