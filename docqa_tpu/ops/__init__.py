from docqa_tpu.ops.norms import layer_norm, rms_norm
from docqa_tpu.ops.rope import apply_rope, rope_angles
from docqa_tpu.ops.attention import attention, flash_attention
from docqa_tpu.ops.topk import merge_topk, sharded_topk

__all__ = [
    "layer_norm",
    "rms_norm",
    "apply_rope",
    "rope_angles",
    "attention",
    "flash_attention",
    "merge_topk",
    "sharded_topk",
]
