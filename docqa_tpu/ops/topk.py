"""Exact top-k over device-sharded score rows.

This is the device-plane replacement for FAISS ``IndexFlatL2.search``
(``semantic-indexer/indexer.py:39``, ``llm-qa/main.py:35``): each device
holds a row shard of the corpus matrix, computes local scores with one MXU
matmul, takes a local ``lax.top_k``, and the k-candidate (score, id) pairs
are merged globally — k*n_shards candidates per query instead of the full
row, so the ICI all-gather is tiny (SURVEY §7 hard part (c)).

Two merge flavors:
  * :func:`merge_topk` — pure function of stacked per-shard results
    (used by the serving path after a gather).
  * :func:`sharded_topk` — runs *inside* ``shard_map``: local top-k then
    ``all_gather`` over the mesh axis + global top-k.  Exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def local_topk(scores, k: int):
    """Per-shard top-k.  scores [q, n_local] -> (vals [q,k], idx [q,k])."""
    k = min(k, scores.shape[-1])
    return jax.lax.top_k(scores, k)


def merge_topk(shard_vals, shard_ids, k: int):
    """Merge per-shard candidates.

    Args:
      shard_vals: [n_shards, q, k_local] scores
      shard_ids:  [n_shards, q, k_local] *global* ids
    Returns (vals [q, k], ids [q, k]) globally exact.
    """
    n_shards, q, k_local = shard_vals.shape
    flat_vals = shard_vals.transpose(1, 0, 2).reshape(q, n_shards * k_local)
    flat_ids = shard_ids.transpose(1, 0, 2).reshape(q, n_shards * k_local)
    vals, pos = jax.lax.top_k(flat_vals, min(k, flat_vals.shape[-1]))
    ids = jnp.take_along_axis(flat_ids, pos, axis=-1)
    return vals, ids


def sharded_topk(scores_local, shard_offset, k: int, axis_name: str):
    """Inside ``shard_map``: local scores -> global exact top-k.

    Args:
      scores_local: [q, n_local] this shard's scores
      shard_offset: scalar int32 — global id of this shard's row 0
      k: fan-in
      axis_name: mesh axis the corpus rows are sharded over
    Returns replicated (vals [q, k], global_ids [q, k]).
    """
    vals, idx = local_topk(scores_local, k)
    gids = idx + shard_offset
    # [n_shards, q, k] on every member after the gather (rides ICI)
    all_vals = jax.lax.all_gather(vals, axis_name)
    all_ids = jax.lax.all_gather(gids, axis_name)
    return merge_topk(all_vals, all_ids, k)
