"""Normalization ops.

Computed in float32 regardless of input dtype (bfloat16 activations lose too
much precision in the variance), cast back on exit — the standard TPU recipe.
XLA fuses these into neighboring matmuls; no Pallas needed here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def layer_norm(x, gamma, beta, eps: float = 1e-12):
    """BERT-style LayerNorm over the last axis (encoder/NER stacks)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)).astype(dtype)


def rms_norm(x, gamma, eps: float = 1e-5):
    """RMSNorm over the last axis (decoder stack, Llama/Mistral-style)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps)
    return (y * gamma.astype(jnp.float32)).astype(dtype)
