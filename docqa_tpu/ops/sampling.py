"""Token sampling for the decode loop.

All functions are jit-compatible (static shapes, no data-dependent Python
control flow).  The reference ran ``temperature=0`` (``llm-qa/main.py:69``),
so greedy is the default; temperature / top-k / top-p cover the rest.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def greedy(logits: jax.Array) -> jax.Array:
    """[b, v] -> [b] int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample(
    logits: jax.Array,
    rng: jax.Array,
    temperature=0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """[b, v] logits -> [b] int32 tokens.

    ``temperature`` may be a Python float (0.0 compiles to pure argmax) or a
    traced scalar — callers serving per-request temperatures pass it traced
    so one compiled program covers every value (the greedy/stochastic split
    stays static).
    """
    if isinstance(temperature, (int, float)) and temperature == 0.0:
        return greedy(logits)
    logits = logits.astype(jnp.float32) / jnp.maximum(
        jnp.asarray(temperature, jnp.float32), 1e-6
    )
    if top_k > 0:
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose cumulative prob (exclusive) is < top_p
        cutoff_mask = cum - probs < top_p
        kth = jnp.where(cutoff_mask, sorted_logits, jnp.inf).min(
            axis=-1, keepdims=True
        )
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
