"""Rotary position embeddings (RoPE) for the decoder stack.

Angles are precomputed once per model (host) and passed in as an array; the
application is a pure elementwise op XLA fuses into the QK projections.
Uses the split-halves convention (Llama/Mistral style, matching HF weights).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_angles(head_dim: int, max_len: int, theta: float = 10000.0):
    """Return (cos, sin), each [max_len, head_dim/2], float32."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    pos = jnp.arange(max_len, dtype=jnp.float32)
    angles = jnp.outer(pos, inv_freq)  # [max_len, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, positions):
    """Rotate q or k.

    Args:
      x: [batch, seq, heads, head_dim]
      cos, sin: [max_len, head_dim/2] tables from :func:`rope_angles`
      positions: [batch, seq] int32 absolute positions (supports ragged
        decode — each lane carries its own offset).  Contract: positions
        MUST be < max_len — JAX gather clamps out-of-bounds indices, so a
        position past the table silently reuses the last row's angles.
        Size tables to the model's max_seq_len (the decode engine bounds
        positions accordingly).
    """
    dtype = x.dtype
    c = cos[positions][:, :, None, :]  # [b, s, 1, hd/2]
    s = sin[positions][:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
