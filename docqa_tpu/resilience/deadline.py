"""End-to-end request deadlines (admission-time budgets).

A :class:`Deadline` is created once, at request admission (``service/app.py``
``POST /ask``), and threaded through every stage the request touches:
``service/qa.py`` → ``engines/dispatch.py`` → ``engines/retrieve.py`` /
``engines/serve.py``.  Each stage calls :meth:`Deadline.check` (or inspects
:meth:`Deadline.remaining`) *before* doing work, so a request that can no
longer finish in time is shed at the first opportunity instead of queueing —
the BENCH_r05 failure mode was exactly requests piling up 7.9 s past any
useful completion time.

Shedding raises :class:`DeadlineExceeded`, a ``TimeoutError`` subclass, so
callers that already handle timeouts keep working, while the HTTP layer can
map it distinctly (504) from a queue-full shed (503).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from time import monotonic
from typing import Optional


class DeadlineExceeded(TimeoutError):
    """The request's end-to-end budget ran out.

    ``stage`` names where the shed happened ("retrieve", "serve_queue",
    "decode", ...) — the observable an operator needs to see *which* stage
    is eating the budget."""

    def __init__(self, stage: str = "", overrun_s: float = 0.0) -> None:
        self.stage = stage
        self.overrun_s = overrun_s
        detail = f" at {stage}" if stage else ""
        super().__init__(
            f"deadline exceeded{detail} (overrun {overrun_s * 1000:.0f} ms)"
        )


@dataclass
class Deadline:
    """A monotonic-clock expiry carried by one request.

    Construct with :meth:`after` at admission; stages only ever *read* it.
    ``None`` is the universal "no deadline" sentinel — every consumer in
    the framework accepts ``deadline=None`` and skips all checks.
    """

    expires_at: float  # time.monotonic() value
    budget_s: float = field(default=0.0)  # original budget (introspection)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(expires_at=monotonic() + seconds, budget_s=seconds)

    def remaining(self) -> float:
        """Seconds left; negative once expired."""
        return self.expires_at - monotonic()

    @property
    def expired(self) -> bool:
        return monotonic() >= self.expires_at

    def check(self, stage: str = "") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is gone.

        A shed is marked on the active trace (docqa_tpu/obs) before the
        raise — the flight recorder always keeps deadline-shed requests,
        and the event names the stage that ran out, so "which stage eats
        the budget" is answerable from one timeline.  Lazy import: the
        shed path is rare and this module must stay import-light."""
        overrun = monotonic() - self.expires_at
        if overrun >= 0:
            from docqa_tpu import obs

            obs.flag("deadline_exceeded")
            obs.event(
                "deadline_exceeded",
                stage=stage,
                overrun_ms=round(overrun * 1000.0, 1),
            )
            raise DeadlineExceeded(stage, overrun)

    def bound(self, timeout: Optional[float]) -> float:
        """Clamp a stage-local wait to the remaining budget (never
        negative — a 0 wait lets pollers fail fast on their own path)."""
        rem = max(self.remaining(), 0.0)
        if timeout is None:
            return rem
        return min(timeout, rem)
