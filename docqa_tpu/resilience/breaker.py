"""Per-dependency circuit breakers.

A breaker wraps one dependency (broker publishes, the deid stage, the
index stage, the decoder, checkpoint loads).  Repeated failures OPEN it;
while open, callers fail fast (:class:`BreakerOpen`) instead of hammering
a dependency that needs a recovery window — and the QA path uses exactly
that fast signal to serve a *degraded* extractive answer while the
decoder is down (``service/qa.py``).

States (the classic three):

* ``closed`` — normal; consecutive failures are counted.
* ``open`` — ``failure_threshold`` consecutive failures seen; every call
  is rejected until ``reset_timeout_s`` elapses.
* ``half_open`` — probation after the timeout: a bounded number of probe
  calls pass through; one success closes the breaker, one failure
  re-opens it (and restarts the timer).

State changes are published to the metrics registry as the gauge
``breaker_<name>_state`` (0 closed / 1 half-open / 2 open) plus
``breaker_<name>_opened`` / ``_rejected`` counters, so ``/metrics``
shows an outage the moment admission starts degrading.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, TypeVar

from docqa_tpu.runtime.metrics import (
    DEFAULT_REGISTRY,
    MetricsRegistry,
    get_logger,
)

log = get_logger("docqa.breaker")

T = TypeVar("T")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(RuntimeError):
    """The dependency's circuit is open — fail fast, don't queue."""

    def __init__(self, name: str, retry_after_s: float) -> None:
        self.breaker_name = name
        self.retry_after_s = retry_after_s
        super().__init__(
            f"circuit '{name}' is open (retry in {retry_after_s:.1f}s)"
        )


class CircuitBreaker:
    """Thread-safe three-state breaker.  ``clock`` is injectable so tests
    drive the reset timeout without sleeping."""

    def __init__(
        self,
        name: str,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_max: int = 1,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = max(1, half_open_max)
        self._registry = registry or DEFAULT_REGISTRY
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0  # consecutive, while closed
        self._opened_at = 0.0
        self._probes = 0  # in-flight probes while half-open
        self._publish_state()

    # ---- state ---------------------------------------------------------------

    def _publish_state(self) -> None:
        self._registry.gauge(f"breaker_{self.name}_state").set(
            _STATE_GAUGE[self._state]
        )

    def _to(self, state: str) -> None:
        if state != self._state:
            log.warning(
                "breaker '%s': %s -> %s", self.name, self._state, state
            )
            self._state = state
            self._publish_state()

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._probes = 0
            self._to(HALF_OPEN)

    # ---- call-side API -------------------------------------------------------

    def allow(self) -> bool:
        """True if a call may proceed (reserves a probe slot when
        half-open)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and self._probes < self.half_open_max:
                self._probes += 1
                return True
            self._registry.counter(f"breaker_{self.name}_rejected").inc()
            return False

    def release_probe(self) -> None:
        """Return an unused half-open probe slot.

        For callers that consumed ``allow()`` but then never ran the
        guarded call to an outcome (shed by other admission control —
        queue full, budget gone): without the release the single probe
        slot would stay reserved and the breaker could wedge half-open
        forever."""
        with self._lock:
            if self._state == HALF_OPEN and self._probes > 0:
                self._probes -= 1

    def raise_if_open(self) -> None:
        if not self.allow():
            with self._lock:
                retry_after = max(
                    0.0,
                    self.reset_timeout_s - (self._clock() - self._opened_at),
                )
            raise BreakerOpen(self.name, retry_after)

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state in (HALF_OPEN, OPEN):
                # OPEN included: a success from a call admitted before the
                # trip (in flight across the transition) proves the
                # dependency lives — no reason to sit out the timeout
                self._to(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._trip_locked()
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._trip_locked()

    def _trip_locked(self) -> None:
        self._opened_at = self._clock()
        self._failures = 0
        self._registry.counter(f"breaker_{self.name}_opened").inc()
        self._to(OPEN)

    def call(self, fn: Callable[[], T]) -> T:
        """Run ``fn`` under the breaker: reject when open, feed the
        outcome back."""
        self.raise_if_open()
        try:
            out = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out


class BreakerBoard:
    """The runtime's named breakers, one per dependency.

    ``get(name)`` lazily creates a breaker with the board's defaults, so
    call sites never have to know the full dependency list up front.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._defaults = dict(
            failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s,
        )
        self._registry = registry
        self._clock = clock
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(name)
            if br is None:
                br = CircuitBreaker(
                    name,
                    registry=self._registry,
                    clock=self._clock,
                    **self._defaults,
                )
                self._breakers[name] = br
            return br

    def adopt(self, breaker: CircuitBreaker) -> CircuitBreaker:
        """Register an externally-owned breaker (module-level singletons
        like the checkpoint loader's) so its state shows up on the same
        status surfaces as the board's own."""
        with self._lock:
            return self._breakers.setdefault(breaker.name, breaker)

    def states(self) -> Dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {name: br.state for name, br in items}
