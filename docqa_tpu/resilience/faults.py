"""Deterministic seeded fault injection.

Every resilience behavior in this repo (retries, breakers, deadline
shedding, degraded-mode QA, broker redelivery) is verified by *injecting*
the failure it handles — at chosen, reproducible steps, not by monkey-
patching internals per test.  Production code calls
:func:`perturb(site)` at its instrumented points; with no active plan
that is one global read and an immediate return.

Instrumented sites (grep ``resilience_site:`` to enumerate):

=====================  =====================================================
``broker.publish``     ``MemoryBroker.publish`` / ``AmqpBroker.publish`` —
                       raising here simulates a dropped broker connection
``extract``            ``DocumentPipeline.ingest_document``, before
                       extraction
``deid``               ``DocumentPipeline._deid_handler``, before the NER
                       batch
``index``              ``DocumentPipeline._index_handler``, before encoding
``decoder``            ``QAService`` generation submission — a raise here is
                       a decoder outage (the degraded-mode trigger)
``checkpoint.load``    ``models/hf_checkpoint.load_checkpoint_dir`` weight
                       read
``serve.worker_loop``  top of every ``ContinuousBatcher`` worker iteration —
                       a raise is a replica worker CRASH (queued requests
                       fail over via the pool, admitted fail typed); a pure
                       delay (``noerror``) is a worker WEDGE (heartbeat goes
                       stale, the pool declares the replica dead)
``serve.decode_chunk`` before each decode chunk's device fetch — a delay is
                       a SLOW-DECODE replica; a raise is a decode failure
                       (typed errors via ``_fail_active``, batcher survives)
=====================  =====================================================

A :class:`FaultPlan` is a list of :class:`FaultRule`; each rule matches a
site and fires either at explicit call indices (``at_steps``) or with
probability ``p`` drawn from a ``random.Random`` seeded by
``(plan.seed, site, call_index)`` — the same plan + seed always perturbs
the same calls.  Rules can raise (:class:`InjectedFault`), sleep
(``delay_s`` — a slow stage), or both.

Activation:

* context manager — ``with FaultPlan([...]):`` (tests);
* environment — ``FaultPlan.from_env()`` parses ``DOCQA_FAULTS`` (spec
  below) and ``DOCQA_FAULTS_SEED``; ``DocQARuntime`` installs it at boot
  when set, so chaos drills run against the real service with zero code.

``DOCQA_FAULTS`` spec: semicolon-separated rules,
``site[:key=value]*`` with keys ``p`` (probability), ``delay`` (seconds),
``steps`` (comma-separated call indices), ``times`` (max fires).  E.g.::

    DOCQA_FAULTS="broker.publish:p=0.2;deid:delay=0.5:p=0.3;decoder:p=1"
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger

log = get_logger("docqa.faults")


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised in production unless
    an operator installed a fault plan)."""

    def __init__(self, site: str, step: int) -> None:
        self.site = site
        self.step = step
        super().__init__(f"injected fault at {site} (call #{step})")


@dataclass(frozen=True)
class FaultRule:
    site: str
    p: float = 0.0  # per-call probability of firing
    at_steps: Tuple[int, ...] = ()  # 0-based call indices that always fire
    delay_s: float = 0.0  # sleep this long when firing (slow stage)
    raise_error: bool = True  # raise InjectedFault when firing
    times: Optional[int] = None  # stop firing after this many hits

    def __post_init__(self):
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"p must be in [0,1], got {self.p}")


class FaultPlan:
    """A deterministic set of fault rules, installable as the process-wide
    active plan (context manager) — one plan at a time, by design: chaos
    tests compose rules into one plan rather than nesting plans."""

    def __init__(self, rules: Sequence[FaultRule], seed: int = 0) -> None:
        self.rules = list(rules)
        self.seed = seed
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}  # per-site call counter
        self._fires: Dict[int, int] = {}  # per-rule fire counter
        self.log: List[Tuple[str, int]] = []  # (site, step) of every fire

    # ---- construction --------------------------------------------------------

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> Optional["FaultPlan"]:
        """Parse ``DOCQA_FAULTS`` / ``DOCQA_FAULTS_SEED``; None when
        unset/empty (the production default)."""
        env = os.environ if env is None else env
        spec = (env.get("DOCQA_FAULTS") or "").strip()
        if not spec:
            return None
        seed = int(env.get("DOCQA_FAULTS_SEED", "0"))
        rules = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            tokens = part.split(":")
            site, kv = tokens[0].strip(), tokens[1:]
            kwargs: Dict[str, object] = {}
            for tok in kv:
                key, _, value = tok.partition("=")
                key = key.strip()
                if key == "p":
                    kwargs["p"] = float(value)
                elif key == "delay":
                    kwargs["delay_s"] = float(value)
                elif key == "steps":
                    kwargs["at_steps"] = tuple(
                        int(s) for s in value.split(",") if s
                    )
                elif key == "times":
                    kwargs["times"] = int(value)
                elif key == "noerror":
                    kwargs["raise_error"] = False
                else:
                    raise ValueError(
                        f"unknown DOCQA_FAULTS key {key!r} in {part!r}"
                    )
            rules.append(FaultRule(site, **kwargs))
        return cls(rules, seed=seed)

    @classmethod
    def seeded_chaos(
        cls,
        seed: int,
        sites: Sequence[str] = ("broker.publish", "deid", "index"),
        p: float = 0.25,
        delay_s: float = 0.0,
    ) -> "FaultPlan":
        """A random-but-seeded plan over ``sites`` (chaos_smoke's diet)."""
        return cls(
            [FaultRule(site, p=p, delay_s=delay_s) for site in sites],
            seed=seed,
        )

    # ---- activation ----------------------------------------------------------

    def __enter__(self) -> "FaultPlan":
        install(self)
        return self

    def __exit__(self, *exc) -> None:
        uninstall(self)

    # ---- the hook ------------------------------------------------------------

    def perturb(self, site: str, sleep=time.sleep) -> None:
        """Called by instrumented code: maybe delay, maybe raise."""
        with self._lock:
            step = self._calls.get(site, 0)
            self._calls[site] = step + 1
            firing: List[Tuple[int, FaultRule]] = []
            for ri, rule in enumerate(self.rules):
                if rule.site != site:
                    continue
                if rule.times is not None and self._fires.get(ri, 0) >= rule.times:
                    continue
                hit = step in rule.at_steps
                if not hit and rule.p > 0.0:
                    # crc32, not hash(): str hashes are randomized per
                    # interpreter run, and the plan must replay across runs
                    rng = random.Random(
                        (self.seed * 1_000_003 + step)
                        ^ zlib.crc32(site.encode())
                        ^ (ri << 16)
                    )
                    hit = rng.random() < rule.p
                if hit:
                    self._fires[ri] = self._fires.get(ri, 0) + 1
                    firing.append((ri, rule))
            if firing:
                self.log.append((site, step))
        for _ri, rule in firing:
            DEFAULT_REGISTRY.counter(f"faults_{site}").inc()
            if rule.delay_s > 0.0:
                log.info(
                    "injected %.0f ms stall at %s (call #%d)",
                    rule.delay_s * 1000, site, step,
                )
                sleep(rule.delay_s)
            if rule.raise_error:
                log.info("injected fault at %s (call #%d)", site, step)
                raise InjectedFault(site, step)


# ---- process-wide active plan ----------------------------------------------

_active_lock = threading.Lock()
_active: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> None:
    global _active
    with _active_lock:
        if _active is not None and _active is not plan:
            raise RuntimeError(
                "a FaultPlan is already active; compose rules into one plan"
            )
        _active = plan


def uninstall(plan: FaultPlan) -> None:
    global _active
    with _active_lock:
        if _active is plan:
            _active = None


def active_plan() -> Optional[FaultPlan]:
    return _active


def perturb(site: str) -> None:
    """The production-code hook: near-zero cost when no plan is active."""
    plan = _active
    if plan is not None:
        plan.perturb(site)
