"""Failure-path engineering for the serving stack.

The reference system has no fault handling at all — services die on a
missed HTTP call and poison messages are silently dropped (PAPER.md
"What the reference is NOT").  BENCH_r05 showed the cost of the happy
path alone: the open-loop QPS-16 run collapsed to ~1 sustained QPS with
7.9 s p95 because requests queued with no deadline, no shedding, and no
fallback.  This package supplies the four primitives every stage of the
pipeline leans on:

* :mod:`deadline` — an end-to-end request budget created at admission
  and threaded through retrieval, dispatch, and the continuous batcher;
  every stage *sheds* instead of queueing past its deadline.
* :mod:`policy` — jittered exponential-backoff retries with a
  deterministic (seeded) jitter so failure tests replay exactly.
* :mod:`breaker` — per-dependency circuit breakers (broker, deid,
  index, decoder, checkpoint loads) that stop hammering a failing
  dependency and give it a recovery window.
* :mod:`faults` — a deterministic seeded fault-injection plan; every
  resilience behavior above is exercised by injecting broker drops,
  slow stages, handler exceptions, and decoder failures at chosen steps
  (``pytest -m faults``, ``scripts/chaos_smoke.py``).

See ``docs/RESILIENCE.md`` for the operator-facing story.
"""

from docqa_tpu.resilience.breaker import (  # noqa: F401
    BreakerBoard,
    BreakerOpen,
    CircuitBreaker,
)
from docqa_tpu.resilience.deadline import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
)
from docqa_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    FaultRule,
    InjectedFault,
    active_plan,
    perturb,
)
from docqa_tpu.resilience.policy import RetryPolicy  # noqa: F401
