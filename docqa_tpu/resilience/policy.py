"""Retry policy: jittered exponential backoff, deterministic by seed.

The broker already backs off *redeliveries* (``MemoryBroker.nack``); this
policy covers the other half — in-place retries of a fallible call (a
broker publish, a checkpoint shard read, a handler's pure phase) *before*
the failure escalates to a nack/dead-letter or a terminal status.

Jitter is deterministic: delay ``i`` is drawn from a ``random.Random``
seeded by ``(seed, attempt)``, so a fault-injected test replays the exact
same schedule every run (the whole point of ``resilience/faults.py``).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type, TypeVar

from docqa_tpu.resilience.deadline import Deadline, DeadlineExceeded
from docqa_tpu.runtime.metrics import DEFAULT_REGISTRY, get_logger

log = get_logger("docqa.resilience")

T = TypeVar("T")


@dataclass(frozen=True)
class RetryPolicy:
    """``call(fn)`` runs ``fn`` up to ``max_attempts`` times.

    * delays: ``base_delay_s * multiplier**i``, capped at ``max_delay_s``,
      each scaled by a deterministic jitter factor in
      ``[1 - jitter, 1 + jitter]``;
    * only ``retry_on`` exceptions are retried — anything else (and
      :class:`DeadlineExceeded`, always) propagates immediately, though
      every call failure still feeds the breaker;
    * a :class:`~docqa_tpu.resilience.deadline.Deadline` stops the loop
      early: no attempt (or sleep) starts past the deadline;
    * a :class:`~docqa_tpu.resilience.breaker.CircuitBreaker` is consulted
      before and fed after every attempt, so repeated failures here are
      exactly what trips the dependency's breaker.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.5  # ± fraction of the nominal delay
    seed: int = 0
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)

    def delay(self, attempt: int) -> float:
        """Deterministic jittered delay after failed attempt ``attempt``
        (1-based)."""
        nominal = min(
            self.base_delay_s * (self.multiplier ** (attempt - 1)),
            self.max_delay_s,
        )
        if not self.jitter:
            return nominal
        rng = random.Random(self.seed * 1_000_003 + attempt)
        return nominal * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))

    def call(
        self,
        fn: Callable[[], T],
        *,
        name: str = "op",
        deadline: Optional[Deadline] = None,
        breaker=None,  # CircuitBreaker (duck-typed; avoids an import cycle)
        sleep: Callable[[float], None] = time.sleep,
    ) -> T:
        last: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            if deadline is not None:
                deadline.check(f"retry:{name}")
            if breaker is not None:
                breaker.raise_if_open()
            try:
                out = fn()
            except DeadlineExceeded:
                raise  # a shed is a decision, not a transient failure
            except Exception as e:
                # EVERY call failure feeds the breaker — a non-retryable
                # error (corrupt checkpoint raising ValueError) is as
                # much an outage signal as a transient IO error; it just
                # isn't worth re-attempting
                if breaker is not None:
                    breaker.record_failure()
                if not isinstance(e, self.retry_on):
                    raise
                last = e
                DEFAULT_REGISTRY.counter(f"retry_{name}_failures").inc()
                if attempt >= self.max_attempts:
                    break
                pause = self.delay(attempt)
                if deadline is not None and deadline.remaining() <= pause:
                    # sleeping would outlive the request: stop retrying and
                    # surface the real failure (not a synthetic timeout)
                    break
                log.warning(
                    "%s failed (attempt %d/%d): %r — retrying in %.0f ms",
                    name, attempt, self.max_attempts, e, pause * 1000,
                )
                sleep(pause)
            else:
                if breaker is not None:
                    breaker.record_success()
                return out
        assert last is not None
        raise last
