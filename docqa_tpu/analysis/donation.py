"""donation: a buffer donated to a jitted call must not be read afterwards.

``jax.jit(..., donate_argnums=...)`` hands the argument's device buffer to
XLA for in-place reuse — the continuous batcher's KV cache and the vector
store's append buffers depend on it (docs/PERF.md).  After the call the
donated array is *deleted*: any later read raises
``RuntimeError: Array has been deleted`` — but only on real backends under
real donation (CPU tests often keep the buffer alive), so the bug class
ships silently and detonates on the TPU.  The safe idiom is rebinding the
result over the donated name (``self._dev = self._append_jit(self._dev,
...)``), which this checker recognizes.

Resolution model (no type inference; unresolvable sites stay silent):

* donated callables are found at ``jax.jit``/``pjit`` call sites carrying
  ``donate_argnums=(...)``/``donate_argnames=(...)`` with literal values,
  tracked through (a) local names — ``fn = jax.jit(step, donate_argnums=
  (0,))`` … ``fn(state, batch)``; (b) ``self.X = jax.jit(...)``
  attributes, called as ``self.X(...)`` from any method of the same
  class (multiple assignments to one attribute union their donated
  positions — the spec-decode/plain branches of the batcher); (c) local
  names assigned from a same-class getter that trivially ``return
  self.X`` (the ``fn = self._get_decode_fn()`` idiom); (d) immediate
  ``jax.jit(f, donate_argnums=...)(args)`` calls.
* at each such call, the argument expression at every donated position
  (a bare name or dotted ``self.…`` chain) is tracked; a READ of that
  exact expression on any later line of the same function flags —
  unless a rebind (assignment to the same name/chain, including tuple
  unpacking of the call's own result) happens on an earlier-or-equal
  line.  Reads inside the donating call itself don't count; line order
  approximates control flow (a loop back-edge read is out of scope),
  EXCEPT that a read in the mutually-exclusive arm of the same ``if``
  as the donating call never flags — exactly one arm executes (the
  spec/non-spec dispatch branches in ``serve.warmup`` donate the same
  fresh buffer from either arm).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
    expr_text,
)

_JIT_NAMES = frozenset({"jit", "pjit"})


def _donated_positions(call: ast.Call) -> Optional[Tuple[Set[int], Set[str]]]:
    """(argnums, argnames) from a jax.jit call, or None when it donates
    nothing / nothing literal."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            for el in _elements(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    nums.add(el.value)
        elif kw.arg == "donate_argnames":
            for el in _elements(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.add(el.value)
    return (nums, names) if (nums or names) else None


def _elements(node: ast.AST) -> Sequence[ast.AST]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return node.elts
    return [node]


def _is_jit_call(fn: FunctionInfo, node: ast.Call) -> bool:
    name = call_name(node)
    if not name:
        return False
    resolved = fn.module.resolve_alias(name)
    return resolved.rsplit(".", 1)[-1] in _JIT_NAMES


def _branch_paths(root: ast.AST) -> Dict[int, Tuple[Tuple[int, str], ...]]:
    """Node id -> chain of ``(id(If node), arm)`` ancestors, where arm is
    ``"body"`` or ``"orelse"``.  Two nodes whose chains disagree on any
    shared If sit in mutually-exclusive arms — at most one executes."""
    paths: Dict[int, Tuple[Tuple[int, str], ...]] = {}

    def visit(node: ast.AST, path: Tuple[Tuple[int, str], ...]) -> None:
        is_if = isinstance(node, ast.If)
        for field_name, field in ast.iter_fields(node):
            children = field if isinstance(field, list) else [field]
            child_path = path
            if is_if and field_name in ("body", "orelse"):
                child_path = path + ((id(node), field_name),)
            for child in children:
                if isinstance(child, ast.AST):
                    paths[id(child)] = child_path
                    visit(child, child_path)

    paths[id(root)] = ()
    visit(root, ())
    return paths


def _mutually_exclusive(
    a: Tuple[Tuple[int, str], ...], b: Tuple[Tuple[int, str], ...]
) -> bool:
    arms = dict(a)
    return any(
        if_id in arms and arms[if_id] != arm for if_id, arm in b
    )


class DonationChecker:
    rule = "donation"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        # class-level donated attributes: (module id, class) -> attr ->
        # (argnums, argnames); plus trivial getters returning them
        attr_donations: Dict[Tuple[int, str], Dict[str, Tuple[Set[int], Set[str]]]] = {}
        getters: Dict[Tuple[int, str], Dict[str, str]] = {}

        for fn in package.functions:
            if fn.class_name is None:
                continue
            cls_key = (id(fn.module), fn.class_name)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ) and _is_jit_call(fn, node.value):
                    donated = _donated_positions(node.value)
                    if donated is None:
                        continue
                    for t in node.targets:
                        text = expr_text(t)
                        if text.startswith("self."):
                            slot = attr_donations.setdefault(cls_key, {})
                            old = slot.get(text)
                            if old:  # union across branches/assignments
                                old[0].update(donated[0])
                                old[1].update(donated[1])
                            else:
                                slot[text] = (
                                    set(donated[0]), set(donated[1])
                                )
            # trivial getter: def _get_x(self): ... return self._x
            for stmt in ast.walk(fn.node):
                if isinstance(stmt, ast.Return) and stmt.value is not None:
                    text = expr_text(stmt.value)
                    if text.startswith("self."):
                        getters.setdefault(cls_key, {})[fn.name] = text

        for fn in package.functions:
            out.extend(self._check_function(fn, attr_donations, getters))
        return out

    # -- per-function ---------------------------------------------------------

    def _check_function(
        self,
        fn: FunctionInfo,
        attr_donations,
        getters,
    ) -> List[Finding]:
        out: List[Finding] = []
        cls_key = (id(fn.module), fn.class_name) if fn.class_name else None
        cls_attrs = attr_donations.get(cls_key, {}) if cls_key else {}
        cls_getters = getters.get(cls_key, {}) if cls_key else {}

        # local donated callables: name -> (argnums, argnames)
        local: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            donated: Optional[Tuple[Set[int], Set[str]]] = None
            if isinstance(value, ast.Call) and _is_jit_call(fn, value):
                donated = _donated_positions(value)
            elif isinstance(value, ast.Call):
                # fn = self._get_decode_fn() -> trivial getter -> attr
                name = call_name(value)
                if name.startswith("self.") and name.count(".") == 1:
                    attr = cls_getters.get(name.split(".", 1)[1])
                    if attr is not None:
                        donated = cls_attrs.get(attr)
            if donated is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local[t.id] = donated

        # find donating calls
        paths = _branch_paths(fn.node)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            donated = None
            name = call_name(node)
            if isinstance(node.func, ast.Call) and _is_jit_call(
                fn, node.func
            ):
                donated = _donated_positions(node.func)
            elif isinstance(node.func, ast.Name):
                donated = local.get(node.func.id)
            elif name.startswith("self."):
                donated = cls_attrs.get(name)
            if donated is None:
                continue
            out.extend(self._check_call(fn, node, donated, paths))
        return out

    def _check_call(
        self,
        fn: FunctionInfo,
        call: ast.Call,
        donated: Tuple[Set[int], Set[str]],
        paths: Dict[int, Tuple[Tuple[int, str], ...]],
    ) -> List[Finding]:
        out: List[Finding] = []
        argnums, argnames = donated
        exprs: List[str] = []
        for i in sorted(argnums):
            if i < len(call.args):
                text = expr_text(call.args[i])
                if text and _is_trackable(call.args[i]):
                    exprs.append(text)
        for kw in call.keywords:
            if kw.arg in argnames:
                text = expr_text(kw.value)
                if text and _is_trackable(kw.value):
                    exprs.append(text)
        if not exprs:
            return out

        call_line = call.lineno
        in_call = {id(n) for n in ast.walk(call)}
        # rebinds: line -> set of rebound expression texts
        rebinds: List[Tuple[int, str]] = []
        reads: List[Tuple[int, str, ast.AST]] = []
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    for el in _flatten_targets(t):
                        text = expr_text(el)
                        if text:
                            rebinds.append((node.lineno, text))
            if isinstance(node, (ast.Name, ast.Attribute)):
                if id(node) in in_call:
                    continue
                if isinstance(getattr(node, "ctx", None), ast.Load):
                    text = expr_text(node)
                    if text in exprs:
                        reads.append((node.lineno, text, node))

        call_path = paths.get(id(call), ())
        for line, text, node in reads:
            if line <= call_line:
                continue
            if _mutually_exclusive(call_path, paths.get(id(node), ())):
                continue  # other arm of the same if: never both execute
            rebound = any(
                rl <= line and rb == text and rl >= call_line
                for rl, rb in rebinds
            )
            if rebound:
                continue
            out.append(
                Finding(
                    self.rule,
                    fn.module.relpath,
                    line,
                    fn.qualname,
                    f"'{text}' read after being donated to the jitted call "
                    f"on line {call_line} (donated buffers are deleted; "
                    f"rebind the result or drop the donation)",
                )
            )
        return out


def _is_trackable(node: ast.AST) -> bool:
    """Only bare names and dotted chains are tracked (a temporary like
    ``jnp.asarray(x)`` cannot be read again)."""
    return bool(dotted_name(node))


def _flatten_targets(node: ast.AST):
    if isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _flatten_targets(el)
    elif isinstance(node, ast.Starred):
        yield from _flatten_targets(node.value)
    else:
        yield node
