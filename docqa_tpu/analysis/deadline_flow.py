"""deadline-flow: request deadlines must thread through, waits must clamp.

PR 1's contract (docs/RESILIENCE.md): a :class:`~docqa_tpu.resilience.
deadline.Deadline` is stamped once at ``/ask`` admission and *threaded*
through every stage; every blocking wait a request performs is clamped to
the remaining budget.  Three sub-rules enforce it:

1. **dropped deadline** — inside a function with a deadline in scope
   (a parameter named ``deadline``/``dl``, a local built via
   ``Deadline.after(...)``/``Deadline(...)``, or a local read from a
   ``….deadline`` attribute), every call to a package function that
   *accepts* a ``deadline`` parameter must pass one.  Calls that forward
   ``**kwargs`` are trusted (the conditional-kwarg idiom in
   ``QAService.ask_submit``).
2. **unclamped wait** — with a deadline in scope, blocking primitives
   (``….wait(…)``, ``….result(…)``, ``….join(…)``, ``….get_many(…)``,
   ``queue.get(timeout=…)``, ``time.sleep(…)``) must derive their timeout
   from the deadline (``.bound(…)`` / ``.remaining(…)`` or a value
   data-flow-derived from one; derivation propagates through assignments
   and ``list.append``).  A blocking call with *no* timeout at all is an
   unbounded wait and always flags.
3. **sleep-polling on the request path** — ``time.sleep`` in a
   request-path module (the ``/ask`` serving chain, see
   :data:`REQUEST_PATH_MODULES`; fixtures opt in with a
   ``# docqa-lint: request-path`` pragma) is flagged regardless of scope:
   the serving path waits on condition variables, never by polling.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    expr_text,
)
from docqa_tpu.analysis.lock_discipline import THREADISH_RE

# The /ask serving chain: admission -> qa -> dispatch -> retrieval ->
# continuous batcher.  Ingest-side workers (pipeline consumers, broker
# internals) run off the request path and may poll at their own cadence.
REQUEST_PATH_MODULES = frozenset(
    {
        "docqa_tpu.service.app",
        "docqa_tpu.service.qa",
        "docqa_tpu.engines.dispatch",
        "docqa_tpu.engines.retrieve",
        "docqa_tpu.engines.rag_fused",
        "docqa_tpu.engines.serve",
        # the pool fronts the batcher on every /ask since PR 6 — its
        # waits are request waits (cv-protocol holds them to a Deadline)
        "docqa_tpu.engines.pool",
    }
)

# Attribute names that block the calling thread.  `.get` is deliberately
# absent (dict.get would drown the signal), and `.join` only counts on
# thread-like receivers or with a timeout= argument (`str.join` /
# `os.path.join` share the attribute name — same filter as
# lock_discipline).
BLOCKING_ATTRS = frozenset({"wait", "result", "join", "get_many"})

DEADLINE_NAME_HINTS = frozenset({"deadline", "dl"})


def _is_deadline_expr(value: ast.AST) -> bool:
    """Expressions that produce a Deadline: ``Deadline.after(...)``,
    ``Deadline(...)``, or a read of a ``….deadline`` attribute."""
    if isinstance(value, ast.Call):
        name = call_name(value)
        return name.split(".")[0] == "Deadline"
    if isinstance(value, ast.Attribute):
        return value.attr == "deadline"
    return False


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _FunctionScan:
    """Per-function dataflow: which names hold deadlines, which names are
    deadline-derived ("clamped") timeouts."""

    def __init__(self, fn: FunctionInfo):
        self.fn = fn
        body = fn.node
        self.deadline_names: Set[str] = {
            p for p in fn.params if p in DEADLINE_NAME_HINTS
        }
        # collect assignments once; nested defs get their own scan
        self.assigns: List[tuple] = []  # (targets: Set[str], value: ast.AST)
        for node in self._walk_shallow(body):
            if isinstance(node, ast.Assign):
                targets: Set[str] = set()
                for t in node.targets:
                    targets |= self._target_names(t)
                self.assigns.append((targets, node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                self.assigns.append(
                    (self._target_names(node.target), node.value)
                )
            elif isinstance(node, ast.AugAssign):
                self.assigns.append(
                    (self._target_names(node.target), node.value)
                )
            elif isinstance(node, ast.Call):
                # x.append(expr) extends x — propagation must see it
                name = call_name(node)
                if name.endswith(".append") and node.args:
                    base = name[: -len(".append")]
                    if "." not in base:
                        self.assigns.append(({base}, node.args[0]))
        for targets, value in self.assigns:
            if _is_deadline_expr(value):
                self.deadline_names |= targets
        self.clamped = self._fixed_point_clamped()

    @staticmethod
    def _target_names(t: ast.AST) -> Set[str]:
        if isinstance(t, ast.Name):
            return {t.id}
        if isinstance(t, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for e in t.elts:
                if isinstance(e, ast.Name):
                    out.add(e.id)
            return out
        return set()

    def _walk_shallow(self, root: ast.AST):
        """Walk the function body without descending into nested defs."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _expr_is_clamped(self, value: ast.AST, clamped: Set[str]) -> bool:
        text = expr_text(value)
        if ".bound(" in text or ".remaining(" in text:
            return True
        return bool(
            _names_in(value) & (clamped | self.deadline_names)
        )

    def _fixed_point_clamped(self) -> Set[str]:
        clamped: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for targets, value in self.assigns:
                if targets <= clamped:
                    continue
                if self._expr_is_clamped(value, clamped):
                    clamped |= targets
                    changed = True
        return clamped

    def has_deadline(self) -> bool:
        return bool(self.deadline_names)

    # positional index of the timeout parameter per blocking primitive
    # (wait(timeout) / result(timeout) / join(timeout) / sleep(secs) take
    # it first; broker get_many(queue, max_n, timeout) takes it third)
    TIMEOUT_POS = {
        "wait": 0,
        "result": 0,
        "join": 0,
        "sleep": 0,
        "get_many": 2,
    }

    def timeout_arg(
        self, node: ast.Call, attr: str
    ) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "timeout":
                return kw.value
        pos = self.TIMEOUT_POS.get(attr, 0)
        if len(node.args) > pos:
            return node.args[pos]
        return None

    def arg_is_clamped(self, arg: ast.AST) -> bool:
        return self._expr_is_clamped(arg, self.clamped)


class DeadlineFlowChecker:
    rule = "deadline-flow"

    def check(self, package: Package) -> List[Finding]:
        accepts_deadline: Dict[str, List[FunctionInfo]] = {}
        for f in package.functions:
            if "deadline" in f.params:
                accepts_deadline.setdefault(f.name, []).append(f)
        out: List[Finding] = []
        for fn in package.functions:
            out.extend(self._check_fn(package, fn, accepts_deadline))
        return out

    # -- per function ---------------------------------------------------------

    def _check_fn(
        self,
        package: Package,
        fn: FunctionInfo,
        accepts_deadline: Dict[str, List[FunctionInfo]],
    ) -> List[Finding]:
        module = fn.module
        request_path = (
            module.name in REQUEST_PATH_MODULES or module.request_path_pragma
        )
        scan = _FunctionScan(fn)
        out: List[Finding] = []
        for node in scan._walk_shallow(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            resolved = module.resolve_alias(name) if name else ""
            is_sleep = resolved == "time.sleep" or resolved.endswith(
                "time.sleep"
            )
            if is_sleep and request_path:
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        node.lineno,
                        fn.qualname,
                        "time.sleep() on the request path — wait on a "
                        "condition/deadline instead of polling",
                    )
                )
                continue
            if not scan.has_deadline():
                continue
            attr = name.rsplit(".", 1)[-1] if name else ""
            receiver = name.rsplit(".", 1)[0] if "." in name else ""
            # 1) dropped deadline
            if attr in accepts_deadline and receiver not in (
                scan.deadline_names
            ):
                callee = package.resolve_call(fn, node)
                passes = any(
                    kw.arg == "deadline" or kw.arg is None  # **kwargs
                    for kw in node.keywords
                ) or any(
                    # positional deadline: a deadline name anywhere in the
                    # argument expression (req.deadline, dl.tighten(), …)
                    # or a deadline-producing expression counts as passing
                    bool(_names_in(a) & scan.deadline_names)
                    or _is_deadline_expr(a)
                    for a in node.args
                )
                if (
                    callee is not None
                    and "deadline" in callee.params
                    and not passes
                ):
                    out.append(
                        Finding(
                            self.rule,
                            module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"call to {attr}() drops the in-scope deadline "
                            "(callee accepts deadline=)",
                        )
                    )
            # 2) unclamped blocking wait
            if attr in BLOCKING_ATTRS or is_sleep:
                if receiver and receiver in scan.deadline_names:
                    continue  # deadline.check/bound/etc on the deadline
                if attr == "join" and not (
                    THREADISH_RE.search(receiver)
                    or any(kw.arg == "timeout" for kw in node.keywords)
                ):
                    continue  # str.join / os.path.join, not a thread join
                arg = scan.timeout_arg(node, "sleep" if is_sleep else attr)
                if arg is None:
                    out.append(
                        Finding(
                            self.rule,
                            module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"{attr or 'sleep'}() without a timeout while a "
                            "deadline is in scope (unbounded wait)",
                        )
                    )
                elif not scan.arg_is_clamped(arg):
                    out.append(
                        Finding(
                            self.rule,
                            module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"{attr or 'sleep'}() timeout is not clamped to "
                            "the in-scope deadline (use deadline.bound/"
                            "remaining)",
                        )
                    )
        return out
