"""retire-once: request retirement happens at DECLARED sites, exactly once.

Every admitted request must reach exactly one terminal retirement — the
``serve._finish`` completion path, a typed shed's cost retirement, the
pool's rescue/shed, or the trace-completion fallback.  PR 16's
preempt-requeue bug class (victim retired twice, or error-stamped and
never finished) motivates making the terminal surface a REVIEWED file:
``retirement_sites.json`` declares every function allowed to invoke a
retirement primitive, so a new terminal site is a ledger diff, not an
accident.  Three sub-rules:

1. **undeclared site** — a call to a retirement primitive (``_finish``,
   or ``retire(...)`` on a cost-ledger receiver) outside a declared
   site function is a finding.  The primitives themselves
   (``serve._finish``, ``RequestCostLedger.retire``) are sites too —
   the ledger names the whole terminal surface;
2. **stale site** — a declared site whose function no longer contains a
   retirement call fails, PR-3 style (the ledger only shrinks by
   editing it deliberately).  Staleness fires only when the declaring
   module is inside the analyzed package — the per-root gate
   (docqa_tpu, then scripts) must not cross-report;
3. **error-set-without-finish** — in any module that binds ``_finish``
   (defines or imports it — i.e. participates in the request lifecycle),
   a function that stamps ``<req>.error = ...`` must reach a terminal
   call (``_finish``/``_retire``/``_fail_active``) later in its body, or
   be declared in the ledger with kind ``error-setter`` (it stamps for
   a caller who finishes).  An error-stamped request nobody finishes
   strands its waiter to the result timeout AND leaks its cost record —
   the exact double fault the dynamic ledger witness hunts.

Double-retire on one straight-line path (two ``_finish(x)`` on the same
request in one block) is flagged as well — the static face of the
witness's double-release check.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    expr_text,
)

LEDGER_NAME = "retirement_sites.json"

# call tails that terminally retire a request (reach _finish and the
# cost-record retirement)
_TERMINAL_TAILS = frozenset({"_finish", "_retire", "_fail_active"})


def default_ledger_path() -> str:
    """The checked-in ledger: ``<repo>/retirement_sites.json``."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), LEDGER_NAME)


def _package_ledger_path(package: Package) -> Optional[str]:
    """Ledger next to the analyzed package's root (fixture trees carry
    their own or none; the real runs resolve to the repo's)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            cand = os.path.join(os.path.dirname(base), LEDGER_NAME)
            if os.path.exists(cand):
                return cand
            cand = os.path.join(base, LEDGER_NAME)
            if os.path.exists(cand):
                return cand
    return None


def load_ledger(path: Optional[str]) -> Dict:
    if not path or not os.path.exists(path):
        return {"sites": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("sites", {})
    return data


def _is_retire_call(node: ast.Call) -> bool:
    """A retirement-primitive call: ``_finish(req)`` (however imported)
    or ``retire(...)`` on a cost-ledger receiver (``DEFAULT_COST_LEDGER.
    retire``, ``obs.DEFAULT_COST_LEDGER.retire``, ``self._ledger.
    retire``)."""
    name = call_name(node)
    if not name:
        return False
    tail = name.rsplit(".", 1)[-1]
    if tail == "_finish":
        return True
    if tail == "retire":
        receiver = name[: -len(".retire")] if "." in name else ""
        return "ledger" in receiver.lower()
    return False


class RetireOnceChecker:
    rule = "retire-once"

    def __init__(self, ledger_path: Optional[str] = None):
        self._ledger_path = ledger_path

    def check(self, package: Package) -> List[Finding]:
        path = (
            self._ledger_path
            or _package_ledger_path(package)
            or default_ledger_path()
        )
        sites: Dict[str, Dict] = load_ledger(path).get("sites", {})
        out: List[Finding] = []
        # which functions actually contain a retirement call
        retiring: Dict[str, FunctionInfo] = {}
        for fn in package.functions:
            key = f"{fn.module.name}:{fn.qualname}"
            for node in self._own_calls(fn):
                if _is_retire_call(node):
                    retiring.setdefault(key, fn)
                    if key not in sites:
                        out.append(
                            Finding(
                                self.rule,
                                fn.module.relpath,
                                node.lineno,
                                fn.qualname,
                                f"undeclared retirement site {key} — "
                                "terminal request retirement must be "
                                "declared in retirement_sites.json",
                            )
                        )
                    break
        # stale declared sites (module in-package, function gone or no
        # longer retiring)
        module_names = {m.name for m in package.modules}
        by_name = {m.name: m for m in package.modules}
        for key in sorted(sites):
            mod = key.split(":", 1)[0]
            if mod not in module_names or key in retiring:
                continue
            if sites[key].get("kind") == "error-setter":
                # error-setters stamp <req>.error for a caller to finish;
                # they need not contain a retirement call themselves, but
                # the function must still exist and still stamp
                fn = self._find_fn(package, key)
                if fn is not None and self._error_assigns(fn):
                    continue
            out.append(
                Finding(
                    self.rule,
                    by_name[mod].relpath,
                    1,
                    "<ledger>",
                    f"stale retirement_sites entry: {key} no longer "
                    "contains a retirement call",
                )
            )
        # error-set-without-finish + straight-line double retire
        for fn in package.functions:
            if not self._binds_finish(fn.module):
                continue
            out.extend(self._check_error_sets(fn, sites))
            out.extend(self._check_double(fn))
        return out

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _own_calls(fn: FunctionInfo):
        stack = list(ast.iter_child_nodes(fn.node))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _find_fn(package: Package, key: str) -> Optional[FunctionInfo]:
        mod, _, qual = key.partition(":")
        for fn in package.functions:
            if fn.module.name == mod and fn.qualname == qual:
                return fn
        return None

    @staticmethod
    def _binds_finish(module) -> bool:
        """The module participates in the request lifecycle: it defines
        or imports ``_finish``.  Everything else (spine items, broker
        messages) has its own error fields and its own checkers."""
        if "_finish" in module.imports:
            return True
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "_finish"
            ):
                return True
        return False

    @staticmethod
    def _error_assigns(fn: FunctionInfo) -> List[ast.Assign]:
        out = []
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "error"
                    and isinstance(t.value, ast.Name)
                ):
                    out.append(node)
        return out

    def _check_error_sets(
        self, fn: FunctionInfo, sites: Dict[str, Dict]
    ) -> List[Finding]:
        assigns = self._error_assigns(fn)
        if not assigns:
            return []
        key = f"{fn.module.name}:{fn.qualname}"
        if sites.get(key, {}).get("kind") == "error-setter":
            return []
        terminal_lines = [
            node.lineno
            for node in self._own_calls(fn)
            if (call_name(node).rsplit(".", 1)[-1] in _TERMINAL_TAILS)
        ]
        out: List[Finding] = []
        for a in assigns:
            if any(line >= a.lineno for line in terminal_lines):
                continue
            out.append(
                Finding(
                    self.rule,
                    fn.module.relpath,
                    a.lineno,
                    fn.qualname,
                    "request error stamped but no terminal call "
                    "(_finish/_retire/_fail_active) follows — the waiter "
                    "strands to its timeout and the cost record leaks "
                    "(declare kind=error-setter in retirement_sites.json "
                    "if a caller finishes it)",
                )
            )
        return out

    def _check_double(self, fn: FunctionInfo) -> List[Finding]:
        """Two _finish calls on the SAME request in one straight-line
        statement block: a guaranteed double-retire attempt (the ledger
        absorbs it at runtime, but the code path is wrong)."""
        out: List[Finding] = []
        for node in ast.walk(fn.node):
            body = getattr(node, "body", None)
            if not isinstance(body, list):
                continue
            seen: Set[str] = set()
            for stmt in body:
                if not (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                ):
                    continue
                call = stmt.value
                if call_name(call).rsplit(".", 1)[-1] != "_finish":
                    continue
                arg = expr_text(call.args[0]) if call.args else ""
                sig = f"_finish({arg})"
                if sig in seen:
                    out.append(
                        Finding(
                            self.rule,
                            fn.module.relpath,
                            stmt.lineno,
                            fn.qualname,
                            f"{sig} called twice on one straight-line "
                            "path — a request retires exactly once",
                        )
                    )
                seen.add(sig)
        return out
