"""ledger-audit: runtime witness of resource acquire/release/retire.

The resource-flow checker proves lifecycle locally (every path of a
function releases what it acquired); ownership that ESCAPES — a table
registered into a slot, a cost record handed to the trace — is exactly
what it cannot follow.  This witness covers that half at runtime, the
way ``race_witness`` covers lock orderings the static graph models:

* ``BlockAllocator.new_table`` / ``BlockTable.release`` are wrapped —
  every KV table's creation records its CALL SITE (the same
  ``path:lineno`` ids ``resource_flow.static_sites`` enumerates), and a
  table still live at quiesce is a leak with the acquiring site named;
* ``RequestCostLedger.open`` / ``retire`` are wrapped — a record opened
  and never retired is a stranded request (the exactly-once-retirement
  invariant retire-once checks the static face of); redundant retires
  (the ledger's first-caller-wins absorbing an idempotent second call)
  are counted but not failures — several shed paths retire defensively
  by design.

``snapshot()`` cross-checks **witnessed ⊆ static**: every witnessed
acquire/release site must be one the static protocol table knows.  A
witnessed site missing from static means resource-flow never analyzed
that acquire — a blind spot to fix or declare, otherwise the static
gate quietly vouches for lifecycles it never walked.

The gate (``scripts/chaos_smoke.py`` under ``--replica-kill``; a served
process exposes the same dump at ``GET /api/ledger`` when booted with
``DOCQA_LEDGER_WITNESS=1``): after quiesce, live tables, unretired
records, or witnessed-site blind spots fail the run.  Overhead is a
dict update per table/record lifecycle event — nothing per token — but
it is still opt-in and never belongs in a latency benchmark.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

# stack frames from these files are machinery, not call sites
_SKIP_FRAME_PARTS = ("ledger_audit.py",)

# witnessed call-site lines may sit a couple of lines off the static
# Call node's anchor (decorators, multi-line calls); match within this
_LINE_TOLERANCE = 2


def build_site_map(
    paths: Optional[List[str]] = None,
) -> Dict[str, Dict[Tuple[str, int], Dict[str, str]]]:
    """protocol -> (abspath, lineno) -> site info, from the SAME
    protocol table resource-flow checks.  ``paths`` defaults to the
    installed ``docqa_tpu`` package + the repo's ``scripts/`` — the
    same scope as ``scripts/lint.py``."""
    from docqa_tpu.analysis.core import Package
    from docqa_tpu.analysis.resource_flow import static_sites

    if paths is None:
        pkg_dir = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        paths = [pkg_dir]
        scripts = os.path.join(os.path.dirname(pkg_dir), "scripts")
        if os.path.isdir(scripts):
            paths.append(scripts)
    out: Dict[str, Dict[Tuple[str, int], Dict[str, str]]] = {}
    for root in paths:
        sites = static_sites(Package.load(root))
        for proto, rows in sites.items():
            table = out.setdefault(proto, {})
            for row in rows:
                key = (os.path.abspath(row["path"]), int(row["line"]))
                table[key] = {
                    "kind": row["kind"],
                    "symbol": row["symbol"],
                    "relpath": row["relpath"],
                }
    return out


def _site_known(
    table: Dict[Tuple[str, int], Dict[str, str]],
    site: Tuple[str, int],
) -> bool:
    path, line = site
    for d in range(_LINE_TOLERANCE + 1):
        if (path, line - d) in table or (path, line + d) in table:
            return True
    return False


class LedgerWitness:
    """Records every KV-table and cost-record lifecycle event."""

    def __init__(
        self,
        site_map: Optional[
            Dict[str, Dict[Tuple[str, int], Dict[str, str]]]
        ] = None,
    ) -> None:
        self.site_map = site_map or {}
        # the REAL primitive, pre-patch: when the race witness is also
        # installed (chaos runs both), a wrapped _mu would inject
        # witness-internal lock-order edges into ITS graph
        from docqa_tpu.analysis.race_witness import _REAL_LOCK

        self._mu = _REAL_LOCK()
        self._seq = 0
        # id(obj) -> {"seq", "site", "symbol"} while live
        self.live_tables: Dict[int, Dict[str, Any]] = {}
        self.live_records: Dict[int, Dict[str, Any]] = {}
        self.counts: Dict[str, int] = {
            "tables_created": 0,
            "tables_released": 0,
            "tables_release_redundant": 0,  # released-table release (safe)
            "tables_release_untracked": 0,  # created before install
            "records_opened": 0,
            "records_retired": 0,
            "records_retire_redundant": 0,  # first-caller-wins absorbed
        }
        # (protocol, abspath, lineno) -> event count
        self.sites: Dict[Tuple[str, str, int], int] = {}
        self._installed = False
        self._orig: Dict[str, Any] = {}

    # ---- recording -----------------------------------------------------------

    def _call_site(self) -> Tuple[str, int]:
        import sys

        frame = sys._getframe(2)
        while frame is not None:
            fname = frame.f_code.co_filename
            if not any(
                p in fname for p in _SKIP_FRAME_PARTS
            ) and not fname.startswith("<"):
                break
            frame = frame.f_back
        if frame is None:
            return ("<unknown>", 0)
        return (os.path.abspath(frame.f_code.co_filename), frame.f_lineno)

    def _event(
        self, proto: str, site: Tuple[str, int]
    ) -> None:
        key = (proto, site[0], site[1])
        self.sites[key] = self.sites.get(key, 0) + 1

    def on_table_created(self, table: Any) -> None:
        site = self._call_site()
        with self._mu:
            self._seq += 1
            self.counts["tables_created"] += 1
            self._event("kv-table", site)
            self.live_tables[id(table)] = {
                "seq": self._seq,
                "site": f"{site[0]}:{site[1]}",
            }

    def on_table_released(self, table: Any, was_released: bool) -> None:
        site = self._call_site()
        with self._mu:
            self._event("kv-table", site)
            if was_released:
                # BlockTable.release is idempotent by design (retire /
                # stop-sweep / failover may all reach a table) — count,
                # don't fail
                self.counts["tables_release_redundant"] += 1
                return
            if self.live_tables.pop(id(table), None) is None:
                self.counts["tables_release_untracked"] += 1
            self.counts["tables_released"] += 1

    def on_record_opened(self, rec: Any) -> None:
        site = self._call_site()
        with self._mu:
            self._seq += 1
            self.counts["records_opened"] += 1
            self._event("cost-record", site)
            self.live_records[id(rec)] = {
                "seq": self._seq,
                "site": f"{site[0]}:{site[1]}",
                "cls": getattr(rec, "cls", "?"),
            }

    def on_record_retired(self, rec: Any, folded: bool) -> None:
        site = self._call_site()
        with self._mu:
            self._event("cost-record", site)
            if folded:
                self.counts["records_retired"] += 1
            else:
                self.counts["records_retire_redundant"] += 1
            self.live_records.pop(id(rec), None)

    # ---- results -------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            counts = dict(self.counts)
            leaked = sorted(
                self.live_tables.values(), key=lambda r: r["seq"]
            )
            unretired = sorted(
                self.live_records.values(), key=lambda r: r["seq"]
            )
            site_items = sorted(self.sites.items())
        missing: List[Dict[str, Any]] = []
        witnessed = []
        for (proto, path, line), n in site_items:
            row = {
                "protocol": proto,
                "site": f"{path}:{line}",
                "events": n,
            }
            witnessed.append(row)
            table = self.site_map.get(proto, {})
            if self.site_map and not _site_known(table, (path, line)):
                missing.append(row)
        return {
            "counts": counts,
            "leaked_tables": leaked,
            "unretired_records": unretired,
            "witnessed_sites": witnessed,
            "static_site_count": sum(
                len(t) for t in self.site_map.values()
            ),
            "sites_missing_from_static": missing,
        }

    # ---- installation --------------------------------------------------------

    def install(self) -> "LedgerWitness":
        """Wrap the lifecycle funnels.  Unlike race_witness this patches
        bound class methods, not factories, so it also covers objects
        whose classes were imported before install."""
        if self._installed:
            return self
        self._installed = True
        witness = self

        from docqa_tpu.engines import paged
        from docqa_tpu.obs import costs

        orig_new_table = paged.BlockAllocator.new_table
        orig_release = paged.BlockTable.release
        orig_open = costs.RequestCostLedger.open
        orig_retire = costs.RequestCostLedger.retire
        self._orig = {
            "new_table": orig_new_table,
            "release": orig_release,
            "open": orig_open,
            "retire": orig_retire,
        }

        def new_table(self):
            table = orig_new_table(self)
            witness.on_table_created(table)
            return table

        def release(self):
            was = bool(self.released)
            orig_release(self)
            witness.on_table_released(self, was)

        def open(self, *a, **kw):
            rec = orig_open(self, *a, **kw)
            if rec is not None:
                witness.on_record_opened(rec)
            return rec

        def retire(self, rec, outcome="ok"):
            folded = orig_retire(self, rec, outcome)
            if rec is not None:
                witness.on_record_retired(rec, folded)
            return folded

        paged.BlockAllocator.new_table = new_table
        paged.BlockTable.release = release
        costs.RequestCostLedger.open = open
        costs.RequestCostLedger.retire = retire
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        from docqa_tpu.engines import paged
        from docqa_tpu.obs import costs

        paged.BlockAllocator.new_table = self._orig["new_table"]
        paged.BlockTable.release = self._orig["release"]
        costs.RequestCostLedger.open = self._orig["open"]
        costs.RequestCostLedger.retire = self._orig["retire"]


# ---------------------------------------------------------------------------
# module-level convenience (chaos_smoke / soak / app endpoint)
# ---------------------------------------------------------------------------

DEFAULT_LEDGER_WITNESS: Optional[LedgerWitness] = None


def install_ledger_witness(
    paths: Optional[List[str]] = None,
) -> LedgerWitness:
    """Build the static site map from the real tree and install a
    process-wide witness.  Idempotent; returns the active witness."""
    global DEFAULT_LEDGER_WITNESS
    if DEFAULT_LEDGER_WITNESS is not None:
        return DEFAULT_LEDGER_WITNESS
    DEFAULT_LEDGER_WITNESS = LedgerWitness(
        site_map=build_site_map(paths)
    ).install()
    return DEFAULT_LEDGER_WITNESS


def ledger_snapshot() -> Optional[Dict[str, Any]]:
    """The active witness's dump (None when no witness is installed)."""
    if DEFAULT_LEDGER_WITNESS is None:
        return None
    return DEFAULT_LEDGER_WITNESS.snapshot()


def maybe_install_from_env() -> Optional[LedgerWitness]:
    """``DOCQA_LEDGER_WITNESS=1`` installs the witness at service boot —
    ``GET /api/ledger`` then serves the live dump."""
    if os.environ.get("DOCQA_LEDGER_WITNESS", "") in ("1", "true", "yes"):
        return install_ledger_witness()
    return None
