"""phi-taint: raw pre-deid text must not reach logs, metrics, or
externally visible payloads.

The clinical contract: extracted document text is PHI until it has been
through ``deid.engine`` (``deidentify_batch``/``anonymize``).  The raw
queue (``raw_queue``) is the ONE sanctioned pre-deid hop — everything
else that leaves the process or lands in an observability surface must
carry masked text only.

Taint model (per function, flow-insensitive fixed point — deliberately
simple; the pipeline's handlers are short):

* **sources** — calls to ``extract_text_ex``/``extract_text``; subscripts
  with the raw-schema key ``["text"]``; iteration/comprehension over a
  tainted collection.  A *nested* function whose body returns a tainted
  expression taints calls it is passed to (the ``retry.call(_extract)``
  idiom).
* **propagation** — assignments (including tuple unpack and
  ``list.append``), f-strings/formatting/concatenation, subscripts of
  tainted values, and any call carrying a tainted argument (except
  content-free builtins: ``len``/``sum``/``bool``/…).
* **sanitizer** — a call whose name ends in ``deidentify_batch``,
  ``deidentify``, ``anonymize`` or ``anonymize_text`` returns clean.
* **sinks** — logging calls (``log.…``/``logger.…``/``logging.…``)
  with a tainted argument; metrics-name construction
  (``….counter/histogram/gauge(tainted)``); broker publishes where the
  queue expression does not mention ``raw`` and the body is tainted;
  HTTP responses (``…json_response(tainted)``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    stmt_walk as _stmt_walk,
)

SOURCE_CALLS = frozenset({"extract_text_ex", "extract_text"})
SOURCE_KEYS = frozenset({"text"})
SANITIZER_SUFFIXES = (
    "deidentify_batch",
    "deidentify",
    "anonymize",
    "anonymize_text",
)
# content-free: the call consumes tainted data but returns nothing that
# can reconstruct it
CLEAN_CALLS = frozenset(
    {"len", "sum", "bool", "enumerate", "range", "id", "hash", "isinstance"}
)
LOG_RECEIVERS = frozenset({"log", "logger", "logging"})
METRIC_ATTRS = frozenset({"counter", "histogram", "gauge"})


class _Taint:
    """Per-function taint state over local names."""

    def __init__(self, fn: FunctionInfo, tainted_fns: Set[str]):
        self.fn = fn
        self.tainted_names: Set[str] = set()
        self.tainted_fns = tainted_fns  # nested defs returning tainted

    def is_sanitizer(self, name: str) -> bool:
        return name.rsplit(".", 1)[-1] in SANITIZER_SUFFIXES or any(
            name.endswith(s) for s in SANITIZER_SUFFIXES
        )

    def tainted(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted_names
        if isinstance(node, ast.Subscript):
            key = node.slice
            if (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and key.value in SOURCE_KEYS
            ):
                return True
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            bare = name.rsplit(".", 1)[-1]
            if self.is_sanitizer(name):
                return False
            if bare in SOURCE_CALLS:
                return True
            if bare in CLEAN_CALLS:
                return False
            # method on a tainted receiver (text.strip()), tainted args,
            # or a tainted-returning function passed as an argument
            if isinstance(node.func, ast.Attribute) and self.tainted(
                node.func.value
            ):
                return True
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if self.tainted(a):
                    return True
                if isinstance(a, ast.Name) and a.id in self.tainted_fns:
                    return True
            return False
        if isinstance(node, ast.JoinedStr):
            return any(
                self.tainted(v.value)
                for v in node.values
                if isinstance(v, ast.FormattedValue)
            )
        if isinstance(node, ast.FormattedValue):
            return self.tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.tainted(v) for v in node.values if v is not None)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.tainted(node.elt) or any(
                self.tainted(g.iter) for g in node.generators
            )
        if isinstance(node, ast.DictComp):
            return self.tainted(node.value)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, ast.Attribute):
            return self.tainted(node.value)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        return False

    def _mark_targets(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted_names.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark_targets(e)

    def fixed_point(self) -> None:
        changed = True
        while changed:
            changed = False
            before = len(self.tainted_names)
            for node in _stmt_walk(self.fn.node):
                if isinstance(node, ast.Assign):
                    if self.tainted(node.value):
                        for t in node.targets:
                            self._mark_targets(t)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if self.tainted(node.value):
                        self._mark_targets(node.target)
                elif isinstance(node, ast.AugAssign):
                    if self.tainted(node.value):
                        self._mark_targets(node.target)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if self.tainted(node.iter):
                        self._mark_targets(node.target)
                elif isinstance(node, ast.Call):
                    name = call_name(node)
                    if (
                        name.endswith(".append")
                        and node.args
                        and self.tainted(node.args[0])
                    ):
                        base = name[: -len(".append")]
                        if "." not in base:
                            self.tainted_names.add(base)
                elif isinstance(node, ast.withitem):
                    pass
            for node in _stmt_walk(self.fn.node):
                if isinstance(
                    node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)
                ):
                    for g in node.generators:
                        if self.tainted(g.iter):
                            self._mark_targets(g.target)
            if len(self.tainted_names) != before:
                changed = True


class PhiTaintChecker:
    rule = "phi-taint"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        # nested defs whose return value is tainted (the _extract idiom):
        # computed with an empty taint env — sources only
        tainted_fns: Set[str] = set()
        for fn in package.functions:
            probe = _Taint(fn, set())
            probe.fixed_point()
            for node in _stmt_walk(fn.node):
                if isinstance(node, ast.Return) and probe.tainted(node.value):
                    tainted_fns.add(fn.name)
                    break
        for fn in package.functions:
            out.extend(self._check_fn(fn, tainted_fns))
        return out

    def _check_fn(
        self, fn: FunctionInfo, tainted_fns: Set[str]
    ) -> List[Finding]:
        module = fn.module
        taint = _Taint(fn, tainted_fns)
        taint.fixed_point()
        out: List[Finding] = []
        for node in _stmt_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            receiver = name.split(".")[0]
            attr = name.rsplit(".", 1)[-1]
            args = list(node.args) + [kw.value for kw in node.keywords]
            any_tainted = any(taint.tainted(a) for a in args)
            if not any_tainted:
                continue
            if receiver in LOG_RECEIVERS and "." in name:
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        node.lineno,
                        fn.qualname,
                        f"raw pre-deid text reaches logging via {name}()",
                    )
                )
            elif attr in METRIC_ATTRS:
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        node.lineno,
                        fn.qualname,
                        f"raw pre-deid text used as a metrics label in "
                        f"{name}()",
                    )
                )
            elif attr in ("publish", "_publish"):
                queue_expr = ""
                if node.args:
                    try:
                        queue_expr = ast.unparse(node.args[0])
                    except Exception:
                        queue_expr = ""
                if "raw" not in queue_expr:
                    out.append(
                        Finding(
                            self.rule,
                            module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"raw pre-deid text published to "
                            f"{queue_expr or 'a queue'} (only the raw queue "
                            "may carry un-deidentified text)",
                        )
                    )
            elif attr == "json_response":
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        node.lineno,
                        fn.qualname,
                        "raw pre-deid text reaches an HTTP response "
                        f"({name}())",
                    )
                )
        return out
