"""Shared concurrency model for the racecheck rule family.

Everything the four thread-safety checkers (``guarded-state``,
``thread-lifecycle``, ``cv-protocol``, ``dispatch-streams``) and the
dynamic witness (``analysis/race_witness.py``) agree on lives here, so
the static and dynamic views can be cross-checked without naming drift:

* **lock discovery** — every ``threading.Lock/RLock/Condition`` (and
  ``multiprocessing.Lock``) assignment, with its *creation site*
  ``(abs_path, lineno)`` so the runtime witness can map a live primitive
  back to the same ``Class.attr`` identity the static graph uses;
* **condition→lock aliases** — ``self._cv = threading.Condition(
  self._lock)`` makes the two names ONE lock; both the static
  acquisition graph and the witnessed graph canonicalize through
  :func:`canonical`, or an edge between the aliases would read as an
  ordering fact about two locks that cannot deadlock against each other;
* **held-at-call-sites inference** — a helper whose every
  package-resolvable call site sits under lock L is treated as running
  with L held (the ``caller holds self._cv`` docstring contract of
  ``serve._pop_free_slots``), so guarded-state and cv-protocol don't
  flag the helper body for the caller's discipline;
* **dispatch reachability** — can a function's transitive package call
  graph reach a jax dispatch (a ``jax.*``/``jnp.*`` call or a function
  jit-purity considers traced)?  Thread-lifecycle uses it to name the
  daemon threads whose un-joined XLA compile aborts the interpreter at
  exit; dispatch-streams uses it to enumerate the process's concurrent
  device streams against the checked-in ledger;
* **thread-entry enumeration** — ``threading.Thread(target=…)``,
  ``executor.submit(…)``, ``loop.run_in_executor(pool, …)`` and
  ``obs.call_in(ctx, fn, …)`` sites, with their resolved targets where
  resolution is possible (``self.method``, bare names, ``partial``);
* **full cycle detection** — iterative DFS over an acquisition-order
  graph returning every elementary cycle once (the 2-cycle-only scan
  PR 2 shipped missed any A→B→C→A order inversion by construction).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from docqa_tpu.analysis.core import (
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)

LOCK_FACTORY_RE = re.compile(
    r"threading\.(?:Lock|RLock|Condition)\b|multiprocessing\.Lock\b"
)
LOCKISH_ATTR_RE = re.compile(r"(?:^|_)(?:lock|cv|mutex|rlock)$|_lock$|_cv$")
CONDITIONISH_ATTR_RE = re.compile(r"(?:^|_)cv$|_cv$|(?:^|\.)cv$|condition$")
EXECUTORISH_RE = re.compile(r"pool|executor", re.IGNORECASE)

LOCK_FACTORY_TAILS = frozenset({"Lock", "RLock", "Condition"})


def _factory_kind(module, value: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' / 'Condition' when ``value`` is a direct
    threading-primitive construction (through import aliases), else
    None.  ``field(default_factory=threading.Condition)`` counts too —
    the *declaration* site names the lock even though construction
    happens inside dataclass machinery."""
    if isinstance(value, ast.Call):
        name = module.resolve_alias(call_name(value))
        tail = name.rsplit(".", 1)[-1]
        head = name.split(".")[0]
        if tail in LOCK_FACTORY_TAILS and head in (
            "threading", "multiprocessing"
        ):
            return tail
        if tail == "field":
            for kw in value.keywords:
                if kw.arg == "default_factory":
                    inner = module.resolve_alias(dotted_name(kw.value))
                    t = inner.rsplit(".", 1)[-1]
                    if t in LOCK_FACTORY_TAILS and inner.split(".")[0] in (
                        "threading", "multiprocessing"
                    ):
                        return t
    return None


@dataclasses.dataclass
class LockDecl:
    """One discovered lock declaration."""

    lock_id: str  # "Class.attr" / module-level name — the graph node id
    kind: str  # Lock | RLock | Condition
    module_relpath: str
    module_abspath: str
    lineno: int  # the factory call's line (witness creation-site key)
    alias_of: Optional[str] = None  # Condition(self._lock) -> "Class._lock"


def _owner_class(package: Package, module, node: ast.AST) -> Optional[str]:
    """Class whose method (usually ``__init__``) contains ``node``."""
    for fn in package.functions:
        if fn.module is not module or fn.class_name is None:
            continue
        lo = getattr(fn.node, "lineno", None)
        hi = getattr(fn.node, "end_lineno", None)
        if lo is not None and hi is not None and lo <= node.lineno <= hi:
            return fn.class_name
    return None


def _memoized(package: Package, key: str, compute):
    """Per-Package memo for the shared fixed points: four checkers run
    over one Package per lint invocation, and lock discovery / call-site
    holding / dispatch reachability are identical across them.  The
    cache lives ON the package object, so it dies with it (no global
    keyed by ``id()`` to go stale)."""
    cache = getattr(package, "_concurrency_memo", None)
    if cache is None:
        cache = {}
        package._concurrency_memo = cache  # type: ignore[attr-defined]
    if key not in cache:
        cache[key] = compute()
    return cache[key]


def discover_lock_attr_names(package: Package) -> Set[str]:
    """Attribute/variable NAMES assigned a threading primitive anywhere
    in the package — the broad, text-matched discovery lock-discipline
    has always used for ``with``-expression classification.  Wider than
    :func:`discover_locks` on purpose: a lock created through a wrapper
    (``X(threading.Lock())``) still names a lock attr here even though
    it has no witness-mappable creation site.  One implementation, one
    regex — lock-discipline and the witness id-map must never drift."""

    def compute() -> Set[str]:
        names: Set[str] = set()
        for module in package.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = getattr(node, "value", None)
                if value is None:
                    continue
                try:
                    text = ast.unparse(value)
                except Exception:
                    continue
                if not LOCK_FACTORY_RE.search(text):
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        names.add(t.attr)
                    elif isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    return _memoized(package, "lock_attr_names", compute)


def discover_locks(package: Package) -> Dict[str, LockDecl]:
    return _memoized(package, "locks", lambda: _discover_locks(package))


def _discover_locks(package: Package) -> Dict[str, LockDecl]:
    """Every lock/cv declaration in the package, keyed by lock id.

    Identity matches ``lock_discipline._lock_id``: ``Class.attr`` for
    ``self.X`` assignments inside a class, the bare target name for
    module-level locks.  Dataclass ``field(default_factory=…)``
    declarations are keyed ``Class.attr`` but carry no usable runtime
    creation site (construction happens inside generated ``__init__``
    code) — the witness leaves those unwrapped by design."""
    out: Dict[str, LockDecl] = {}
    for module in package.modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if value is None:
                continue
            kind = _factory_kind(module, value)
            if kind is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Attribute) and isinstance(
                    t.value, ast.Name
                ) and t.value.id == "self":
                    cls = _owner_class(package, module, node)
                    lock_id = f"{cls}.{t.attr}" if cls else t.attr
                elif isinstance(t, ast.Attribute):
                    lock_id = t.attr
                elif isinstance(t, ast.Name):
                    cls = _owner_class(package, module, node)
                    # AnnAssign inside a class body (dataclass field):
                    # the name is an attribute of the class
                    lock_id = f"{cls}.{t.id}" if cls else t.id
                else:
                    continue
                alias_of = None
                if (
                    kind == "Condition"
                    and isinstance(value, ast.Call)
                    and value.args
                ):
                    # Condition(self._lock): the cv IS that lock
                    inner = dotted_name(value.args[0])
                    if inner.startswith("self.") and lock_id.count("."):
                        alias_of = (
                            f"{lock_id.rsplit('.', 1)[0]}."
                            f"{inner.rsplit('.', 1)[-1]}"
                        )
                    elif inner:
                        alias_of = inner
                out.setdefault(
                    lock_id,
                    LockDecl(
                        lock_id=lock_id,
                        kind=kind,
                        module_relpath=module.relpath,
                        module_abspath=module.path,
                        lineno=value.lineno,
                        alias_of=alias_of,
                    ),
                )
    return out


def lock_aliases(locks: Dict[str, LockDecl]) -> Dict[str, str]:
    return {
        lid: d.alias_of for lid, d in locks.items() if d.alias_of
    }


def canonical(lock_id: str, aliases: Dict[str, str]) -> str:
    """Resolve a lock id through the cv→lock alias chain (bounded)."""
    seen = set()
    while lock_id in aliases and lock_id not in seen:
        seen.add(lock_id)
        lock_id = aliases[lock_id]
    return lock_id


def lock_id_for(fn: FunctionInfo, expr_text: str) -> str:
    """The ONE lock-identity convention (static checkers + witness map):
    ``Class.attr`` for ``self.…`` expressions, receiver text otherwise."""
    attr = expr_text.rsplit(".", 1)[-1]
    if expr_text.startswith("self.") and fn.class_name:
        return f"{fn.class_name}.{attr}"
    return expr_text


def is_lock_expr(text: str, known: Set[str]) -> bool:
    if not text:
        return False
    attr = text.rsplit(".", 1)[-1]
    return attr in known or bool(LOCKISH_ATTR_RE.search(attr))


def known_lock_attrs(locks: Dict[str, LockDecl]) -> Set[str]:
    return {lid.rsplit(".", 1)[-1] for lid in locks}


# ---------------------------------------------------------------------------
# held-lock regions
# ---------------------------------------------------------------------------


def direct_with_locks(
    fn: FunctionInfo, known_attrs: Set[str]
) -> Set[str]:
    """Lock ids this function acquires via ``with`` directly (no calls)."""
    out: Set[str] = set()
    stack = list(ast.iter_child_nodes(fn.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    continue
                try:
                    text = ast.unparse(item.context_expr)
                except Exception:
                    continue
                if is_lock_expr(text, known_attrs):
                    out.add(lock_id_for(fn, text))
    return out


def held_at_call_sites(
    package: Package, known_attrs: Set[str]
) -> Dict[int, Set[str]]:
    return _memoized(
        package,
        ("held_at_call_sites", tuple(sorted(known_attrs))),
        lambda: _held_at_call_sites(package, known_attrs),
    )


def _held_at_call_sites(
    package: Package, known_attrs: Set[str]
) -> Dict[int, Set[str]]:
    """fn-node-id -> locks held at EVERY package-resolvable call site of
    that function (∅ when any site is lock-free or no site resolves).

    This is the "caller holds the lock" inference: a helper like
    ``serve._pop_free_slots`` (docstring: caller holds ``_cv``) is only
    ever invoked under the lock, so its body runs guarded even though it
    never acquires anything.  Computed to a FIXED POINT so the
    convention chains: ``_compose_live_locked`` called only from other
    ``*_locked`` helpers inherits the lock their callers hold."""
    # callee-node-id -> [(caller-node-id, directly-held-locks)] per site
    sites: Dict[int, List[Tuple[int, Set[str]]]] = {}

    for fn in package.functions:

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if isinstance(item.context_expr, ast.Call):
                            continue
                        try:
                            text = ast.unparse(item.context_expr)
                        except Exception:
                            continue
                        if is_lock_expr(text, known_attrs):
                            new_held = new_held + (
                                lock_id_for(fn, text),
                            )
                if isinstance(child, ast.Call):
                    callee = package.resolve_call(fn, child)
                    if callee is not None:
                        sites.setdefault(id(callee.node), []).append(
                            (id(fn.node), set(new_held))
                        )
                visit(child, new_held)

        visit(fn.node, ())

    out: Dict[int, Set[str]] = {}
    changed = True
    while changed:
        changed = False
        for node_id, call_list in sites.items():
            effective = [
                held | out.get(caller_id, set())
                for caller_id, held in call_list
            ]
            common = set.intersection(*effective) if effective else set()
            if common and common != out.get(node_id, set()):
                out[node_id] = common
                changed = True
    return out


# ---------------------------------------------------------------------------
# dispatch reachability
# ---------------------------------------------------------------------------

_JAX_HEADS = ("jax",)

# method names that ALWAYS mean device work in this codebase even when
# the receiver's type can't be resolved: every `warmup` compiles and
# dispatches (batcher shape ladder, engine decode programs) — the
# compile-storm threads are exactly the ones the stream ledger must see
_DISPATCHING_ATTRS = frozenset({"warmup"})


def _is_dispatching_call(module, node: ast.Call) -> Optional[str]:
    """A call that enqueues device work (or compiles): anything through
    the jax namespace (``jnp.…``, ``jax.…``, ``lax.…`` via import
    aliases).  Pure-shape helpers are indistinguishable without types —
    conservative is correct here: the question is whether the THREAD can
    own a device stream at all."""
    name = call_name(node)
    if not name:
        return None
    if name.rsplit(".", 1)[-1] in _DISPATCHING_ATTRS:
        return f"{name} (compile/dispatch by convention)"
    resolved = module.resolve_alias(name)
    head = resolved.split(".")[0]
    if head in _JAX_HEADS and "." in resolved:
        return resolved
    return None


def dispatch_reachable(package: Package) -> Dict[int, str]:
    return _memoized(
        package, "dispatch_reachable", lambda: _dispatch_reachable(package)
    )


def _dispatch_reachable(package: Package) -> Dict[int, str]:
    """fn-node-id -> first jax-dispatching call (its dotted text)
    reachable from the function through package-resolvable calls.

    Class constructions resolve to ``__init__`` (``ContinuousBatcher(…)``
    from the pool monitor allocates a KV cache — that IS a dispatch on
    the monitor thread), and jit roots count as dispatching even when
    their bodies contain no direct jax call (invoking the compiled
    object dispatches)."""
    # class name -> __init__ FunctionInfo
    inits: Dict[str, FunctionInfo] = {}
    for fn in package.functions:
        if fn.name == "__init__" and fn.class_name:
            inits.setdefault(fn.class_name, fn)

    from docqa_tpu.analysis.jit_purity import discover_jit_roots

    roots, root_lambdas = discover_jit_roots(package)

    reach: Dict[int, str] = {}
    for node_id, (fn, _via) in roots.items():
        reach[node_id] = f"jit root {fn.qualname}"
    for fn in package.functions:
        if id(fn.node) in reach:
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                hit = _is_dispatching_call(fn.module, node)
                if hit is not None:
                    reach[id(fn.node)] = hit
                    break

    def callees(fn: FunctionInfo) -> Iterable[FunctionInfo]:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = package.resolve_call(fn, node)
            if callee is None:
                name = call_name(node)
                tail = name.rsplit(".", 1)[-1]
                callee = inits.get(tail)
            if callee is not None:
                yield callee

    changed = True
    while changed:
        changed = False
        for fn in package.functions:
            if id(fn.node) in reach:
                continue
            for callee in callees(fn):
                sub = reach.get(id(callee.node))
                if sub is not None:
                    reach[id(fn.node)] = f"via {callee.qualname} ({sub})"
                    changed = True
                    break
    return reach


# ---------------------------------------------------------------------------
# thread entry points
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ThreadEntry:
    """One place the process grows a thread of control."""

    kind: str  # "thread" | "executor" | "call_in"
    module_relpath: str
    lineno: int
    site_qualname: str  # function containing the spawn
    target: Optional[FunctionInfo]  # resolved entry function, or None
    target_text: str  # source text of the target expression
    daemon: bool
    thread_name: str  # name= kwarg when present
    binding: Optional[str]  # "self.X" / local name the Thread lands in

    @property
    def key(self) -> str:
        """Stable ledger key: the resolved target when available (two
        sites spawning the same loop are one stream class), else the
        spawning site."""
        if self.target is not None:
            return (
                f"{self.target.module.relpath}:{self.target.qualname}"
            )
        return f"{self.module_relpath}:{self.site_qualname}"


def _resolve_target(
    package: Package, fn: FunctionInfo, target: ast.AST, depth: int = 0
) -> Optional[FunctionInfo]:
    if depth > 4 or target is None:
        return None
    if isinstance(target, ast.Call):
        name = call_name(target)
        if name.rsplit(".", 1)[-1] == "partial" and target.args:
            return _resolve_target(package, fn, target.args[0], depth + 1)
        return None
    if isinstance(target, ast.Lambda):
        # scan the lambda body for the one resolvable call
        for node in ast.walk(target.body):
            if isinstance(node, ast.Call):
                resolved = package.resolve_call(fn, node)
                if resolved is not None:
                    return resolved
        return None
    fake = ast.Call(func=target, args=[], keywords=[])
    ast.copy_location(fake, target)
    return package.resolve_call(fn, fake)


def module_scope_fn(module) -> FunctionInfo:
    """Pseudo-FunctionInfo for module-level statements (the soak script
    builds its thread list at module scope)."""
    return FunctionInfo(
        module=module, node=module.tree, qualname="<module>",
        class_name=None,
    )


def _module_level_nodes(module) -> Iterable[ast.AST]:
    stack = list(ast.iter_child_nodes(module.tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def enumerate_thread_entries(package: Package) -> List[ThreadEntry]:
    return _memoized(
        package,
        "thread_entries",
        lambda: _enumerate_thread_entries(package),
    )


def _enumerate_thread_entries(package: Package) -> List[ThreadEntry]:
    # keyed by creation site so a spawn inside a nested def is attributed
    # once, to the INNERMOST scope (collector order: outer first, so the
    # nested visit overwrites)
    found: Dict[Tuple[str, int, str], ThreadEntry] = {}

    def record(entry: ThreadEntry) -> None:
        found[(entry.module_relpath, entry.lineno, entry.kind)] = entry

    scopes = [(fn, ast.walk(fn.node)) for fn in package.functions] + [
        (module_scope_fn(m), _module_level_nodes(m))
        for m in package.modules
    ]
    for fn, nodes in scopes:
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            resolved = fn.module.resolve_alias(name)
            tail = name.rsplit(".", 1)[-1]
            if resolved == "threading.Thread" or resolved.endswith(
                "threading.Thread"
            ):
                target = None
                daemon = False
                tname = ""
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
                    elif kw.arg == "daemon":
                        daemon = bool(
                            isinstance(kw.value, ast.Constant)
                            and kw.value.value
                        )
                    elif kw.arg == "name" and isinstance(
                        kw.value, ast.Constant
                    ):
                        tname = str(kw.value.value)
                record(
                    ThreadEntry(
                        kind="thread",
                        module_relpath=fn.module.relpath,
                        lineno=node.lineno,
                        site_qualname=fn.qualname,
                        target=_resolve_target(package, fn, target),
                        target_text=(
                            ast.unparse(target) if target is not None else ""
                        ),
                        daemon=daemon,
                        thread_name=tname,
                        binding=None,  # filled by thread_lifecycle
                    )
                )
            elif tail == "submit" and "." in name and EXECUTORISH_RE.search(
                name.rsplit(".", 1)[0]
            ):
                target = node.args[0] if node.args else None
                record(
                    ThreadEntry(
                        kind="executor",
                        module_relpath=fn.module.relpath,
                        lineno=node.lineno,
                        site_qualname=fn.qualname,
                        target=_resolve_target(package, fn, target),
                        target_text=(
                            ast.unparse(target) if target is not None else ""
                        ),
                        daemon=False,
                        thread_name="",
                        binding=None,
                    )
                )
            elif tail == "run_in_executor" and len(node.args) >= 2:
                target = node.args[1]
                record(
                    ThreadEntry(
                        kind="executor",
                        module_relpath=fn.module.relpath,
                        lineno=node.lineno,
                        site_qualname=fn.qualname,
                        target=_resolve_target(package, fn, target),
                        target_text=ast.unparse(target),
                        daemon=False,
                        thread_name="",
                        binding=None,
                    )
                )
            elif tail == "call_in" and len(node.args) >= 2:
                # obs.call_in(ctx, fn, …): runs fn on an executor thread
                # with the trace context attached
                target = node.args[1]
                record(
                    ThreadEntry(
                        kind="call_in",
                        module_relpath=fn.module.relpath,
                        lineno=node.lineno,
                        site_qualname=fn.qualname,
                        target=_resolve_target(package, fn, target),
                        target_text=ast.unparse(target),
                        daemon=False,
                        thread_name="",
                        binding=None,
                    )
                )
    return sorted(
        found.values(), key=lambda e: (e.module_relpath, e.lineno)
    )


# ---------------------------------------------------------------------------
# cycle detection
# ---------------------------------------------------------------------------


def find_cycles(
    edges: Iterable[Tuple[str, str]], limit: int = 64
) -> List[List[str]]:
    """Every elementary cycle in the directed graph, each reported once
    with its smallest node first (deterministic).  Iterative DFS with a
    path stack — the graphs here are a dozen nodes, so no Johnson's
    machinery is needed; ``limit`` bounds pathological fixtures."""
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        if a == b:
            continue
        graph.setdefault(a, []).append(b)
    for v in graph.values():
        v.sort()
    cycles: List[List[str]] = []
    seen: Set[Tuple[str, ...]] = set()

    def canon_cycle(path: Sequence[str]) -> Tuple[str, ...]:
        i = path.index(min(path))
        return tuple(path[i:]) + tuple(path[:i])

    for start in sorted(graph):
        # DFS from `start`, only through nodes >= start (each cycle is
        # found from its smallest node exactly once)
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        while stack and len(cycles) < limit:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    key = canon_cycle(path)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(path) + [start])
                elif nxt > start and nxt not in path and len(path) < 16:
                    stack.append((nxt, path + [nxt]))
    return cycles
