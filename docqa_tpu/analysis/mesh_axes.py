"""mesh-axes: every named mesh axis a device-plane program uses must exist.

The mesh layer (`runtime/mesh.py`) fixes the axis names once — ``MeshConfig
.data_axis = "data"``, ``.model_axis = "model"`` — and every
``PartitionSpec``, ``NamedSharding``, ``shard_map`` spec and ``lax``
collective refers to them by string.  A misspelled axis name is the worst
kind of sharding bug: GSPMD treats an unknown axis as "replicate", the
program still compiles and returns correct numbers, and the only symptom
is an 8x memory/step-time regression a benchmark may or may not catch
(the silent-replication failure mode from the TPU-serving literature —
PAPERS.md entries on ragged paged attention and Gemma serving).

Two sub-rules, both pure-AST:

* **declared axes** — the set of axis names the package declares:
  string defaults of ``*_axis`` config fields/assignments (``data_axis:
  str = "data"``) and literal axis-name tuples of ``Mesh(...)``
  constructions.  Every string literal in axis position — a
  ``PartitionSpec``/``P`` argument (tuple elements included), an
  ``axis_name=`` keyword anywhere, a ``lax`` collective's axis argument —
  must be a declared axis.  A ``P(...)`` argument that is a local Name
  assigned from a string literal is checked through the assignment;
  parameters and attribute reads (``mesh.model_axis``) are trusted.

* **collective binding** — ``lax.psum/ppermute/all_gather/all_to_all/
  axis_index/...`` may only run inside a ``shard_map`` body, over an axis
  the enclosing ``shard_map`` binds.  Bodies are resolved the same way
  jit-purity resolves traced roots (bare names, nested defs,
  ``functools.partial`` aliases — the ``ring_attention_local`` /
  ``_search_kernel`` idioms), and the walk follows package-resolvable
  calls with a parameter-binding environment so ``sharded_topk(...,
  axis)`` two helpers down still maps back to the axis the ``shard_map``
  site bound.  A collective in a function never reached from any
  ``shard_map`` body flags as "outside shard_map"; a literal axis that
  the enclosing site's specs do not mention flags as "not bound".
  Non-literal axes that cannot be proven either way stay silent
  (heuristic checker: no guessing).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Module,
    Package,
    call_name,
    expr_text,
)

# lax collectives with the mesh-axis argument position (keyword is always
# ``axis_name``); everything else defaults to positional arg 1.
COLLECTIVES = frozenset(
    {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "ppermute",
        "pshuffle",
        "all_gather",
        "all_to_all",
        "psum_scatter",
        "axis_index",
        "axis_size",
    }
)
_AXIS_ARG_POS = {"axis_index": 0, "axis_size": 0}
_LIT = "lit:"  # token namespace for string literals


def _is_partition_spec(module: Module, node: ast.Call) -> bool:
    resolved = module.resolve_alias(call_name(node))
    return resolved.rsplit(".", 1)[-1] == "PartitionSpec"


def _is_collective(module: Module, node: ast.Call) -> Optional[str]:
    """The collective's bare name, or None.  Requires the call to resolve
    into jax (``jax.lax.psum``, ``lax.psum``, or a ``from jax.lax import
    psum`` alias) so a package helper named ``psum`` never matches."""
    name = call_name(node)
    if not name:
        return None
    resolved = module.resolve_alias(name)
    tail = resolved.rsplit(".", 1)[-1]
    if tail not in COLLECTIVES:
        return None
    if resolved == tail:  # bare, un-imported name: not jax.lax
        return None
    head = resolved.split(".")[0]
    if head != "jax" and "lax" not in resolved.split("."):
        return None
    return tail


def _axis_expr(tail: str, node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "axis_name":
            return kw.value
    pos = _AXIS_ARG_POS.get(tail, 1)
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _literal_assignments(scope: ast.AST) -> Dict[str, str]:
    """name -> string literal, for simple ``ax = "model"`` assignments."""
    out: Dict[str, str] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


class MeshAxesChecker:
    rule = "mesh-axes"

    # -- declared axes --------------------------------------------------------

    def _declared_axes(self, package: Package) -> Set[str]:
        declared: Set[str] = set()
        for module in package.modules:
            for node in ast.walk(module.tree):
                # config-field / local defaults: data_axis: str = "data"
                if isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                    if (
                        isinstance(target, ast.Name)
                        and target.id.endswith("_axis")
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                    ):
                        declared.add(value.value)
                elif isinstance(node, ast.Assign):
                    if isinstance(node.value, ast.Constant) and isinstance(
                        node.value.value, str
                    ):
                        for t in node.targets:
                            if isinstance(t, ast.Name) and t.id.endswith(
                                "_axis"
                            ):
                                declared.add(node.value.value)
                elif isinstance(node, ast.Call):
                    # Mesh(devices, ("data", "model")) / axis_names=(...)
                    resolved = module.resolve_alias(call_name(node))
                    if resolved.rsplit(".", 1)[-1] != "Mesh":
                        continue
                    names_arg: Optional[ast.AST] = None
                    if len(node.args) > 1:
                        names_arg = node.args[1]
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            names_arg = kw.value
                    if isinstance(names_arg, (ast.Tuple, ast.List)):
                        for el in names_arg.elts:
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                declared.add(el.value)
                    elif isinstance(names_arg, ast.Constant) and isinstance(
                        names_arg.value, str
                    ):
                        declared.add(names_arg.value)
        return declared

    # -- checker entry --------------------------------------------------------

    def check(self, package: Package) -> List[Finding]:
        declared = self._declared_axes(package)
        out: List[Finding] = []

        # innermost functions first (the collector appends outer defs before
        # the defs nested in them), module pseudo-scopes last: a spec inside
        # a nested def is attributed to the nearest enclosing def, and the
        # per-node marker keeps the wider walks from re-reporting it
        scopes: List[FunctionInfo] = list(reversed(package.functions))
        for module in package.modules:
            scopes.append(
                FunctionInfo(
                    module=module, node=module.tree, qualname="<module>",
                    class_name=None,
                )
            )

        # ---- sub-rule 1: literal axis names resolve to declared axes ----
        seen: Set[int] = set()  # wider scopes re-walk nested functions
        for fn in scopes:
            local_lits = _literal_assignments(fn.node)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if id(node) in seen:
                    continue
                if _is_partition_spec(fn.module, node):
                    seen.add(id(node))
                    for arg in node.args:
                        elts = (
                            arg.elts
                            if isinstance(arg, (ast.Tuple, ast.List))
                            else [arg]
                        )
                        for el in elts:
                            lit: Optional[str] = None
                            where = el
                            if isinstance(el, ast.Constant) and isinstance(
                                el.value, str
                            ):
                                lit = el.value
                            elif isinstance(el, ast.Name):
                                lit = local_lits.get(el.id)
                            if lit is not None and lit not in declared:
                                out.append(self._finding(
                                    fn, where,
                                    f"PartitionSpec axis '{lit}' is not a "
                                    f"declared mesh axis "
                                    f"(declared: {self._fmt(declared)})",
                                ))
                else:
                    for kw in node.keywords:
                        if kw.arg != "axis_name":
                            continue
                        if isinstance(kw.value, ast.Constant) and isinstance(
                            kw.value.value, str
                        ) and kw.value.value not in declared:
                            seen.add(id(node))
                            out.append(self._finding(
                                fn, kw.value,
                                f"axis_name '{kw.value.value}' is not a "
                                f"declared mesh axis "
                                f"(declared: {self._fmt(declared)})",
                            ))

        # ---- sub-rule 2: collective binding ----
        out.extend(self._check_collectives(package, declared))
        return out

    # -- collective binding ---------------------------------------------------

    def _check_collectives(
        self, package: Package, declared: Set[str]
    ) -> List[Finding]:
        out: List[Finding] = []
        visited: Set[Tuple[int, Tuple[Tuple[str, str], ...]]] = set()
        # every Call node scanned under some shard_map body walk: the
        # "outside shard_map" pass below flags collectives NOT in this set
        scanned: Set[int] = set()
        # (body owner fn, body node, param->token env, bound tokens,
        #  lexically-enclosing scope for closure/alias lookups)
        frontier: List[
            Tuple[FunctionInfo, ast.AST, Dict[str, str], Set[str],
                  FunctionInfo]
        ] = []

        scopes: List[FunctionInfo] = list(reversed(package.functions))
        for module in package.modules:
            scopes.append(
                FunctionInfo(
                    module=module, node=module.tree, qualname="<module>",
                    class_name=None,
                )
            )

        sm_seen: Set[int] = set()
        for fn in scopes:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) or id(node) in sm_seen:
                    continue
                sm_seen.add(id(node))
                resolved = fn.module.resolve_alias(call_name(node))
                if resolved.rsplit(".", 1)[-1] != "shard_map":
                    continue
                if not node.args:
                    continue
                bound = self._bound_tokens(fn, node)
                target, env = self._resolve_body(
                    package, fn, node.args[0], {}
                )
                if target is None:
                    continue
                body_fn, body_node = target
                frontier.append((body_fn, body_node, env, bound, fn))

        while frontier:
            fn, body, env, bound, home = frontier.pop()
            key = (id(body), tuple(sorted(env.items())))
            if key in visited:
                continue
            visited.add(key)
            # closure reads resolve in the lexically-enclosing scope: a
            # nested body's axis names ARE the enclosing function's locals
            local_lits = _literal_assignments(home.node)
            local_lits.update(_literal_assignments(body))
            bound_lits = {
                t[len(_LIT):] for t in bound if t.startswith(_LIT)
            }
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                scanned.add(id(node))
                tail = _is_collective(fn.module, node)
                if tail is not None:
                    token = self._token(
                        _axis_expr(tail, node), env, local_lits
                    )
                    if token is None:
                        continue
                    if token.startswith(_LIT):
                        lit = token[len(_LIT):]
                        if bound_lits and lit not in bound_lits:
                            out.append(self._finding(
                                fn, node,
                                f"collective {tail}() over axis '{lit}' not "
                                f"bound by the enclosing shard_map "
                                f"(binds: {self._fmt(bound_lits)})",
                            ))
                        elif lit not in declared:
                            out.append(self._finding(
                                fn, node,
                                f"collective {tail}() over axis '{lit}', "
                                f"not a declared mesh axis "
                                f"(declared: {self._fmt(declared)})",
                            ))
                    # non-literal tokens: ok when they textually match a
                    # bound token; unprovable otherwise -> silent
                    continue
                # follow package calls with a rebuilt parameter env
                callee_env: Dict[str, str] = {}
                callee = self._resolve_call_env(
                    package, fn, node, env, local_lits, callee_env, home
                )
                if callee is not None:
                    frontier.append(
                        (callee, callee.node, callee_env, bound, callee)
                    )

        # ---- collectives never reached from any shard_map body ----
        checked: Set[int] = set()
        for fn in scopes:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call) or id(node) in checked:
                    continue
                tail = _is_collective(fn.module, node)
                if tail is None:
                    continue
                checked.add(id(node))
                if id(node) in scanned:
                    continue
                out.append(self._finding(
                    fn, node,
                    f"collective {tail}() outside any shard_map body "
                    f"(collectives need a bound mesh axis)",
                ))
        return out

    # -- token / body resolution ----------------------------------------------

    def _token(
        self,
        expr: Optional[ast.AST],
        env: Dict[str, str],
        local_lits: Dict[str, str],
    ) -> Optional[str]:
        if expr is None:
            return None
        if isinstance(expr, ast.Constant):
            return (
                _LIT + expr.value if isinstance(expr.value, str) else None
            )
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            if expr.id in local_lits:
                return _LIT + local_lits[expr.id]
            return expr.id
        text = expr_text(expr)
        return text or None

    def _bound_tokens(self, fn: FunctionInfo, call: ast.Call) -> Set[str]:
        """Axis tokens THIS shard_map site binds: the PartitionSpec
        arguments of its own ``in_specs``/``out_specs`` (chased through
        local Name assignments and ``specs.append(...)`` list building —
        the ``in_specs = [seq_spec, ...]`` idiom), plus an explicit
        ``axis_name=`` keyword.  Per-site, so two shard_maps in one
        function check their bodies against their OWN axes, not the
        union.  Falls back to every spec in the enclosing function only
        when the site's spec expressions resolve to nothing (specs built
        by a helper)."""
        bound: Set[str] = set()
        local_lits = _literal_assignments(fn.node)

        def add_spec_call(node: ast.Call) -> None:
            for arg in node.args:
                elts = (
                    arg.elts
                    if isinstance(arg, (ast.Tuple, ast.List))
                    else [arg]
                )
                for el in elts:
                    if isinstance(el, ast.Constant):
                        if isinstance(el.value, str):
                            bound.add(_LIT + el.value)
                    elif isinstance(el, ast.Name):
                        if el.id in local_lits:
                            bound.add(_LIT + local_lits[el.id])
                        bound.add(el.id)
                    else:
                        text = expr_text(el)
                        if text:
                            bound.add(text)

        def collect(expr: ast.AST, depth: int) -> None:
            """P-calls in ``expr``, chasing Names through assignments and
            list ``.append``/``.extend`` mutations in ``fn``."""
            if depth > 4:
                return
            names: List[str] = []
            for node in ast.walk(expr):
                if isinstance(node, ast.Call) and _is_partition_spec(
                    fn.module, node
                ):
                    add_spec_call(node)
                elif isinstance(node, ast.Name):
                    names.append(node.id)
            for name in names:
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in node.targets
                    ):
                        if node.value is not expr:
                            collect(node.value, depth + 1)
                    elif (
                        isinstance(node, ast.Call)
                        and call_name(node)
                        in (f"{name}.append", f"{name}.extend")
                        and node.args
                    ):
                        collect(node.args[0], depth + 1)

        spec_exprs: List[ast.AST] = list(call.args[1:])
        for kw in call.keywords:
            if kw.arg in ("in_specs", "out_specs"):
                spec_exprs.append(kw.value)
            elif kw.arg == "axis_name":
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str
                ):
                    bound.add(_LIT + kw.value.value)
                else:
                    text = expr_text(kw.value)
                    if text:
                        bound.add(text)
        for expr in spec_exprs:
            collect(expr, 0)
        if not bound:
            # specs came from a helper: the whole-function walk is the
            # best (over-)approximation left
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call) and _is_partition_spec(
                    fn.module, node
                ):
                    add_spec_call(node)
        return bound

    def _resolve_body(
        self,
        package: Package,
        fn: FunctionInfo,
        target: ast.AST,
        prebound: Dict[str, str],
        depth: int = 0,
    ) -> Tuple[Optional[Tuple[FunctionInfo, ast.AST]], Dict[str, str]]:
        """Resolve a shard_map body expression to (FunctionInfo, body node)
        plus the axis-token env its params were pre-bound with (through
        ``functools.partial``/alias chains)."""
        if depth > 6:
            return None, {}
        if isinstance(target, ast.Lambda):
            lam_fn = FunctionInfo(
                module=fn.module,
                node=target,
                qualname=f"{fn.qualname}.<lambda>",
                class_name=fn.class_name,
            )
            return (lam_fn, target), dict(prebound)
        if isinstance(target, ast.Call):
            name = call_name(target)
            tail = name.rsplit(".", 1)[-1]
            if tail == "partial" and target.args:
                env = dict(prebound)
                lits = _literal_assignments(fn.node)
                for kw in target.keywords:
                    tok = self._token(kw.value, {}, lits)
                    if kw.arg and tok:
                        env[kw.arg] = tok
                return self._resolve_body(
                    package, fn, target.args[0], env, depth + 1
                )
            if tail in ("jit", "pjit", "shard_map") and target.args:
                return self._resolve_body(
                    package, fn, target.args[0], prebound, depth + 1
                )
            return None, {}
        if isinstance(target, (ast.Name, ast.Attribute)):
            fake = ast.Call(func=target, args=[], keywords=[])
            ast.copy_location(fake, target)
            resolved = package.resolve_call(fn, fake)
            if resolved is not None:
                env = {}
                params = resolved.params
                # positional prebinds from partial(...) args are rare for
                # bodies; keyword prebinds map by name
                for p in params:
                    if p in prebound:
                        env[p] = prebound[p]
                return (resolved, resolved.node), env
            if isinstance(target, ast.Name):
                # alias chain: wrapped = kernel / kernel = partial(f, ...)
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not any(
                        isinstance(t, ast.Name) and t.id == target.id
                        for t in node.targets
                    ):
                        continue
                    if node.value is target:
                        continue
                    return self._resolve_body(
                        package, fn, node.value, prebound, depth + 1
                    )
        return None, {}

    def _resolve_call_env(
        self,
        package: Package,
        fn: FunctionInfo,
        node: ast.Call,
        env: Dict[str, str],
        local_lits: Dict[str, str],
        callee_env: Dict[str, str],
        home: FunctionInfo,
    ) -> Optional[FunctionInfo]:
        """Resolve a call inside a shard_map body and populate the callee's
        param->token env from the call's arguments (and any partial-alias
        prebinding on the way).  ``home`` is the lexically-enclosing scope:
        ``fn = functools.partial(helper, axis_name=ax)`` aliases live
        there, not in the nested body."""
        prebound: Dict[str, str] = {}
        callee = package.resolve_call(fn, node)
        if callee is None:
            name = call_name(node)
            if name and "." not in name:
                resolved = self._resolve_body(
                    package, home, node.func, {},
                )
                if resolved[0] is not None and not isinstance(
                    resolved[0][1], ast.Lambda
                ):
                    callee = resolved[0][0]
                    prebound = resolved[1]
        if callee is None:
            return None
        params = callee.params
        if callee.class_name is not None and params[:1] == ["self"]:
            params = params[1:]
        for p, tok in prebound.items():
            callee_env[p] = tok
        for i, arg in enumerate(node.args):
            # positional args fill params not pre-bound by partial kwargs
            free = [p for p in params if p not in prebound]
            if i < len(free):
                tok = self._token(arg, env, local_lits)
                if tok:
                    callee_env[free[i]] = tok
        for kw in node.keywords:
            if kw.arg and kw.arg in params:
                tok = self._token(kw.value, env, local_lits)
                if tok:
                    callee_env[kw.arg] = tok
        return callee

    # -- plumbing -------------------------------------------------------------

    @staticmethod
    def _fmt(names: Set[str]) -> str:
        return ", ".join(sorted(names)) if names else "none"

    def _finding(self, fn: FunctionInfo, node: ast.AST, msg: str) -> Finding:
        return Finding(
            self.rule,
            fn.module.relpath,
            getattr(node, "lineno", 1),
            fn.qualname,
            msg,
        )
