"""wire-consumer: every wire read resolves to a declared producer key.

The spec-shape technique applied to the service wire: producers and
consumers of the same payload live in DIFFERENT modules by design
(``service/pipeline.py`` publishes broker bodies its own Consumer
handlers read back; ``scripts/soak.py`` reads HTTP payloads
``service/app.py`` serves; ``scripts/perf_gate.py`` resolves dotted
paths into ``bench.py``'s DETAILS sections) — nothing structural keeps
the key names in sync, so this rule cross-references package-wide
producer facts against every consumer read:

* **HTTP** — a function whose body calls ``urlopen`` and returns
  ``json.loads(...)`` (bare or as one tuple element) is a fetch helper;
  an ``(await session.get(url)).json()`` chain tags the same way.  The
  call site's literal/f-string URL (f-string holes normalize to ``{}``,
  query strings strip) must match exactly one ``api_contract.json``
  route — an unmatched URL is an undeclared-endpoint finding — and the
  payload variable is then tagged with that entry's response tree:
  every ``var["k"]`` / ``var.get("k")`` read must name a declared key
  (``"*"`` maps accept anything).  Tags flow through assignment,
  iteration, ``zip``, slicing, and comprehensions; an unresolvable
  value is simply untagged — ambiguity never guesses.
* **broker** — ``publish(queue, {...})`` / ``_publish(queue, {...})``
  dict literals are producer facts per queue (queues normalize to the
  literal value or the trailing config attribute name);
  ``Consumer(broker, queue, handler)`` wires a handler whose first
  body-batch parameter reads are checked against that queue's producer
  keys.  A producer key NO wired consumer reads is an orphan finding at
  the publish site — schema freight nobody consumes is drift waiting
  to be load-bearing.
* **bench details** — ``DETAILS["section"] = {...}`` literals in
  ``bench.py`` (in-package or resolved next to the contract) close a
  section's key set; dotted-path string literals anywhere in the
  package (``"qa_e2e.p50_ms"``) and ``perf_baseline.json`` entry paths
  whose first segment names a closed section must name one of its keys.
  Call-assigned, ``.update(non-literal)``, and variable-keyed sections
  stay open and are never checked.
* **journal** — in functions whose qualname mentions ``journal`` or
  ``replay``, a variable assigned from ``json.loads(...)`` carries the
  contract's ``journal_record`` spec; undeclared reads flag.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Any, Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)
from docqa_tpu.analysis.wire_schema import (
    LEDGER_NAME,
    load_contract,
    resolve_contract_path,
    sibling_path,
    spec_child,
)

_DOTTED_RE = re.compile(r"^[A-Za-z_]\w*(\.[A-Za-z_]\w*)+$")
_METHODS = frozenset({"GET", "POST", "PUT", "DELETE", "PATCH"})


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _str_template(node: ast.AST) -> Optional[str]:
    """Literal / f-string / ``a + b`` string expression -> template with
    ``{}`` holes; None when no literal part survives."""
    s = _const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                parts.append("{}")
            else:
                return None
        return "".join(parts)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left = _str_template(node.left)
        right = _str_template(node.right)
        left = "{}" if left is None else left
        right = "{}" if right is None else right
        if left == "{}" and right == "{}":
            return None
        return left + right
    return None


# ---------------------------------------------------------------------------
# contract route matching
# ---------------------------------------------------------------------------


def _route_sample(path: str) -> str:
    """Contract path -> a concrete sample ("/api/trace/{trace_id}" ->
    "/api/trace/X") the template regex is matched against."""
    return re.sub(r"\{[^}]*\}", "X", path)


def match_endpoint(
    template: str,
    endpoints: Dict[str, Any],
    method: Optional[str] = None,
) -> Tuple[Optional[str], bool]:
    """(matched endpoint key or None, matched-anything flag).

    The template's literal query string is stripped, ``{}`` holes become
    ``.*``; it must match exactly one contract route's sample path (or
    several whose specs are identical — then the first wins).
    """
    t = template.split("?", 1)[0]
    if "/" not in t:
        return None, True  # not a URL-ish string: out of scope
    parts = re.split(r"(\{\})", t)
    body = "".join(
        ".*" if p == "{}" else re.escape(p) for p in parts if p
    )
    pattern = re.compile(("^" if t.startswith("/") else "^.*") + body + "$")
    hits = []
    for key in sorted(endpoints):
        m, _, path = key.partition(" ")
        if method is not None and m != method:
            continue
        if pattern.match(_route_sample(path)):
            hits.append(key)
    if len(hits) == 1:
        return hits[0], True
    if len(hits) > 1:
        specs = {
            json.dumps(endpoints[k].get("response"), sort_keys=True)
            for k in hits
        }
        if len(specs) == 1:
            return hits[0], True
        return None, True  # ambiguous: tag nothing, flag nothing
    return None, False


# ---------------------------------------------------------------------------
# spec environment: tag propagation + read checking inside one function
# ---------------------------------------------------------------------------


class _SpecEnv:
    """name -> spec node (dict tree / [elem] / scalar str / None)."""

    def __init__(self) -> None:
        self.specs: Dict[str, Tuple[Any, str]] = {}  # name -> (spec, origin)

    def tag(self, name: str, spec: Any, origin: str) -> None:
        if spec is None:
            self.specs.pop(name, None)
        else:
            self.specs[name] = (spec, origin)

    def spec_of(self, node: ast.AST) -> Optional[Tuple[Any, str]]:
        """Spec carried by an expression: a tagged Name, a slice of a
        tagged list, an index into a tagged list."""
        if isinstance(node, ast.Name):
            return self.specs.get(node.id)
        if isinstance(node, ast.Subscript):
            base = self.spec_of(node.value)
            if base is None:
                return None
            spec, origin = base
            if isinstance(spec, list) and len(spec) == 1:
                if isinstance(node.slice, ast.Slice):
                    return spec, origin
                if _const_str(node.slice) is None:
                    return spec[0], origin
            return None
        return None


def _iter_elem(env: _SpecEnv, it: ast.AST) -> Optional[Tuple[Any, str]]:
    """Spec of one element when iterating ``it``."""
    got = env.spec_of(it)
    if got is None:
        return None
    spec, origin = got
    if isinstance(spec, list) and len(spec) == 1:
        return spec[0], origin
    return None


def _bind_loop(env: _SpecEnv, target: ast.AST, it: ast.AST) -> None:
    if isinstance(target, ast.Name):
        elem = _iter_elem(env, it)
        if elem is not None:
            env.tag(target.id, elem[0], elem[1])
        return
    # for a, b, c in zip(xs, ys, zs)
    if (
        isinstance(target, ast.Tuple)
        and isinstance(it, ast.Call)
        and call_name(it).rsplit(".", 1)[-1] == "zip"
        and len(it.args) == len(target.elts)
    ):
        for tgt, arg in zip(target.elts, it.args):
            if isinstance(tgt, ast.Name):
                elem = _iter_elem(env, arg)
                if elem is not None:
                    env.tag(tgt.id, elem[0], elem[1])


class _ReadChecker:
    """Shared read-checking over a tagged environment."""

    def __init__(self, rule: str, fn: FunctionInfo):
        self.rule = rule
        self.fn = fn
        self.findings: List[Finding] = []
        self.consumed: List[Tuple[str, str]] = []  # (origin, key)

    def _flag(self, node: ast.AST, key: str, origin: str) -> None:
        if self.fn.module.is_suppressed(self.rule, node.lineno):
            return
        self.findings.append(
            Finding(
                self.rule,
                self.fn.module.relpath,
                node.lineno,
                self.fn.qualname,
                f"reads key '{key}' that no producer declares for "
                f"{origin}",
            )
        )

    def check_read(
        self, env: _SpecEnv, node: ast.AST, key: str, base: ast.AST
    ) -> Optional[Tuple[Any, str]]:
        got = env.spec_of(base)
        if got is None:
            return None
        spec, origin = got
        if not isinstance(spec, dict):
            return None
        self.consumed.append((origin, key))
        child = spec_child(spec, key)
        if child is None:
            self._flag(node, key, origin)
            return None
        return child, origin

    def walk(self, env: _SpecEnv, root: ast.AST) -> None:
        """Three passes: (1+2) propagate tags through assignments and
        loops to a fixpoint, (3) check every subscript/.get read."""
        for _ in range(2):
            for node in ast.walk(root):
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    _bind_loop(env, node.target, node.iter)
                elif isinstance(node, ast.comprehension):
                    _bind_loop(env, node.target, node.iter)
                elif isinstance(node, ast.Assign) and len(
                    node.targets
                ) == 1 and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    value = node.value
                    # var = tagged["k"] / tagged.get("k", d) propagates
                    sub = self._read_spec(env, value)
                    if sub is not None:
                        env.tag(tgt, sub[0], sub[1])
                    else:
                        got = env.spec_of(value)
                        if got is not None:
                            env.tag(tgt, got[0], got[1])
        for node in ast.walk(root):
            if isinstance(node, ast.Subscript):
                key = _const_str(node.slice)
                if key is not None:
                    self.check_read(env, node, key, node.value)
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "get"
                    and node.args
                ):
                    key = _const_str(node.args[0])
                    if key is not None:
                        self.check_read(env, node, key, func.value)

    def _read_spec(
        self, env: _SpecEnv, value: ast.AST
    ) -> Optional[Tuple[Any, str]]:
        """Spec of ``tagged["k"]`` / ``tagged.get("k")`` expressions
        (silent: checking happens in the read pass)."""
        if isinstance(value, ast.Subscript):
            key = _const_str(value.slice)
            if key is not None:
                got = env.spec_of(value.value)
                if got is not None and isinstance(got[0], dict):
                    child = spec_child(got[0], key)
                    if child is not None:
                        return child, got[1]
        if isinstance(value, ast.Call):
            func = value.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "get"
                and value.args
            ):
                key = _const_str(value.args[0])
                if key is not None:
                    got = env.spec_of(func.value)
                    if got is not None and isinstance(got[0], dict):
                        child = spec_child(got[0], key)
                        if child is not None:
                            return child, got[1]
        return None


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------


def _queue_id(node: ast.AST) -> Optional[str]:
    """Literal queue name, or the trailing attribute of a config chain
    (``cfg.broker.raw_queue`` -> ``raw_queue``).  A bare Name is a local
    variable — no fact (the ``_publish(queue, body)`` forwarding helper
    must not register a queue called 'queue')."""
    s = _const_str(node)
    if s is not None:
        return s
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class WireConsumerChecker:
    rule = "wire-consumer"

    def __init__(
        self,
        ledger_path: Optional[str] = None,
        bench_path: Optional[str] = None,
        perf_baseline_path: Optional[str] = None,
    ):
        self._ledger_path = ledger_path
        self._bench_path = bench_path
        self._perf_baseline_path = perf_baseline_path

    def check(self, package: Package) -> List[Finding]:
        contract = load_contract(
            resolve_contract_path(package, self._ledger_path)
        )
        out: List[Finding] = []
        out.extend(self._http_checks(package, contract))
        out.extend(self._broker_checks(package))
        out.extend(self._bench_checks(package))
        out.extend(self._journal_checks(package, contract))
        return out

    # -- HTTP -----------------------------------------------------------------

    @staticmethod
    def _fetch_helpers(package: Package) -> Dict[str, Optional[int]]:
        """helper bare name -> tuple index of the JSON payload in its
        return value (None = the whole return IS the payload)."""
        helpers: Dict[str, Optional[int]] = {}
        for fn in package.functions:
            has_urlopen = any(
                isinstance(n, ast.Call)
                and call_name(n).rsplit(".", 1)[-1] == "urlopen"
                for n in ast.walk(fn.node)
            )
            if not has_urlopen:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                v = node.value
                if WireConsumerChecker._is_json_loads(v):
                    helpers[fn.name] = None
                elif isinstance(v, ast.Tuple):
                    for i, elt in enumerate(v.elts):
                        if WireConsumerChecker._is_json_loads(elt):
                            helpers[fn.name] = i
                            break
        return helpers

    @staticmethod
    def _is_json_loads(node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and call_name(node).rsplit(
            ".", 1
        )[-1] == "loads"

    @staticmethod
    def _call_url(call: ast.Call) -> Optional[str]:
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            t = _str_template(a)
            if t is not None and "/" in t:
                return t
        return None

    @staticmethod
    def _call_method(call: ast.Call) -> Optional[str]:
        for a in call.args:
            s = _const_str(a)
            if s in _METHODS:
                return s
        return None

    def _http_payload_call(
        self,
        node: ast.AST,
        helpers: Dict[str, Optional[int]],
        endpoints: Dict[str, Any],
    ) -> Optional[Tuple[Any, str, Optional[int], ast.AST]]:
        """If ``node`` is a tagged-payload-producing expression, return
        (spec, endpoint key, tuple index or None, URL-carrying node)."""
        # unwrap awaits
        while isinstance(node, ast.Await):
            node = node.value
        if not isinstance(node, ast.Call):
            return None
        tail = call_name(node).rsplit(".", 1)[-1]
        if tail in helpers:
            url = self._call_url(node)
            if url is None:
                return None
            key, matched = match_endpoint(
                url, endpoints, self._call_method(node)
            )
            if key is None:
                return ("<nomatch>", url, None, node) if not matched else None
            spec = endpoints[key].get("response")
            if spec is None:
                return None
            return spec, key, helpers[tail], node
        if tail == "json" and isinstance(node.func, ast.Attribute):
            # (await session.get(url)).json()
            inner: Any = node.func.value
            while isinstance(inner, ast.Await):
                inner = inner.value
            if isinstance(inner, ast.Call):
                m = call_name(inner).rsplit(".", 1)[-1].upper()
                if m in _METHODS:
                    url = self._call_url(inner)
                    if url is not None:
                        key, matched = match_endpoint(url, endpoints, m)
                        if key is None:
                            if not matched:
                                return "<nomatch>", url, None, inner
                            return None
                        spec = endpoints[key].get("response")
                        if spec is None:
                            return None
                        return spec, key, None, inner
        return None

    def _http_checks(
        self, package: Package, contract: Dict[str, Any]
    ) -> List[Finding]:
        endpoints = contract.get("endpoints", {})
        if not endpoints:
            return []
        helpers = self._fetch_helpers(package)
        out: List[Finding] = []
        for fn in package.functions:
            env = _SpecEnv()
            checker = _ReadChecker(self.rule, fn)
            for node in ast.walk(fn.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.Expr):
                    value = node.value
                if value is None:
                    continue
                got = self._http_payload_call(value, helpers, endpoints)
                if got is None:
                    continue
                spec, key, idx, url_node = got
                if spec == "<nomatch>":
                    if not fn.module.is_suppressed(
                        self.rule, node.lineno
                    ):
                        out.append(
                            Finding(
                                self.rule,
                                fn.module.relpath,
                                node.lineno,
                                fn.qualname,
                                f"HTTP request to '{key}' matches no "
                                f"route in {LEDGER_NAME}",
                            )
                        )
                    continue
                for tgt in targets:
                    if isinstance(tgt, ast.Name) and idx is None:
                        env.tag(tgt.id, spec, key)
                    elif isinstance(tgt, ast.Tuple) and idx is not None:
                        if idx < len(tgt.elts) and isinstance(
                            tgt.elts[idx], ast.Name
                        ):
                            env.tag(tgt.elts[idx].id, spec, key)
            if env.specs:
                checker.walk(env, fn.node)
            out.extend(checker.findings)
        return out

    # -- broker ---------------------------------------------------------------

    def _broker_checks(self, package: Package) -> List[Finding]:
        producers: Dict[str, Dict[str, Any]] = {}
        sites: Dict[Tuple[str, str], Tuple[FunctionInfo, int]] = {}
        consumers: Dict[str, str] = {}  # handler bare name -> queue
        for fn in package.functions:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                tail = call_name(node).rsplit(".", 1)[-1]
                if tail in ("publish", "_publish") and len(
                    node.args
                ) >= 2 and isinstance(node.args[1], ast.Dict):
                    q = _queue_id(node.args[0])
                    if q is None:
                        continue
                    spec = producers.setdefault(q, {})
                    for k, v in zip(
                        node.args[1].keys, node.args[1].values
                    ):
                        key = _const_str(k) if k is not None else None
                        if key is None:
                            continue
                        sub: Any = "any"
                        if isinstance(v, ast.Dict):
                            sub = {
                                sk: "any"
                                for sk in (
                                    _const_str(x)
                                    for x in v.keys
                                    if x is not None
                                )
                                if sk is not None
                            }
                        prev = spec.get(key)
                        if prev is None or prev == "any":
                            spec[key] = sub if prev is None else "any"
                        elif sub == "any":
                            spec[key] = "any"
                        sites.setdefault(
                            (q, key), (fn, node.lineno)
                        )
                elif tail == "Consumer" and len(node.args) >= 3:
                    q = _queue_id(node.args[1])
                    h = dotted_name(node.args[2]).rsplit(".", 1)[-1]
                    if q is not None and h:
                        consumers[h] = q
        if not producers or not consumers:
            return []
        out: List[Finding] = []
        consumed: Dict[str, Set[str]] = {}
        analyzed_queues: Set[str] = set()
        for fn in package.functions:
            q = consumers.get(fn.name)
            if q is None or q not in producers:
                continue
            params = [
                p for p in fn.params if p not in ("self", "cls")
            ]
            if not params:
                continue
            analyzed_queues.add(q)
            env = _SpecEnv()
            # first param: the batch of bodies
            env.tag(params[0], [producers[q]], f"queue '{q}'")
            checker = _ReadChecker(self.rule, fn)
            checker.walk(env, fn.node)
            out.extend(checker.findings)
            for origin, key in checker.consumed:
                if origin == f"queue '{q}'":
                    consumed.setdefault(q, set()).add(key)
        for q in sorted(analyzed_queues):
            orphan = set(producers[q]) - consumed.get(q, set())
            for key in sorted(orphan):
                fn, lineno = sites[(q, key)]
                if fn.module.is_suppressed(self.rule, lineno):
                    continue
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        lineno,
                        fn.qualname,
                        f"publishes key '{key}' to queue '{q}' that no "
                        "wired consumer reads — orphaned producer key",
                    )
                )
        return out

    # -- bench details / dotted paths -----------------------------------------

    def _bench_facts(
        self, package: Package
    ) -> Dict[str, Optional[Set[str]]]:
        """section -> closed key set, or None when the section is open
        (call-assigned / non-literal update)."""
        trees: List[ast.AST] = [
            m.tree
            for m in package.modules
            if m.name.rsplit(".", 1)[-1] == "bench"
        ]
        if not trees:
            path = self._bench_path or sibling_path(package, "bench.py")
            if path:
                try:
                    with open(path, encoding="utf-8") as f:
                        trees = [ast.parse(f.read(), filename=path)]
                except (OSError, SyntaxError):
                    trees = []
        facts: Dict[str, Optional[Set[str]]] = {}
        for tree in trees:
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "DETAILS"
                ):
                    section = _const_str(node.targets[0].slice)
                    if section is None:
                        continue
                    if isinstance(node.value, ast.Dict):
                        keys = {
                            k
                            for k in (
                                _const_str(x)
                                for x in node.value.keys
                                if x is not None
                            )
                            if k is not None
                        }
                        prev = facts.get(section)
                        if section in facts and prev is None:
                            continue
                        facts[section] = (prev or set()) | keys
                    else:
                        facts[section] = None
                elif isinstance(node, ast.Call):
                    func = node.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr == "update"
                        and isinstance(func.value, ast.Subscript)
                        and isinstance(func.value.value, ast.Name)
                        and func.value.value.id == "DETAILS"
                    ):
                        section = _const_str(func.value.slice)
                        if section is None:
                            continue
                        if node.args and isinstance(
                            node.args[0], ast.Dict
                        ) and not node.keywords:
                            keys = {
                                k
                                for k in (
                                    _const_str(x)
                                    for x in node.args[0].keys
                                    if x is not None
                                )
                                if k is not None
                            }
                            prev = facts.get(section)
                            if section in facts and prev is None:
                                continue
                            facts[section] = (prev or set()) | keys
                        else:
                            facts[section] = None
        return facts

    def _bench_checks(self, package: Package) -> List[Finding]:
        facts = self._bench_facts(package)
        closed = {s for s, keys in facts.items() if keys is not None}
        if not closed:
            return []
        out: List[Finding] = []

        def check_path(
            dotted: str, relpath: str, lineno: int, symbol: str,
            module=None,
        ) -> None:
            head, _, rest = dotted.partition(".")
            if head not in closed or not rest:
                return
            key = rest.split(".", 1)[0]
            if key in facts[head]:  # type: ignore[operator]
                return
            if module is not None and module.is_suppressed(
                self.rule, lineno
            ):
                return
            out.append(
                Finding(
                    self.rule,
                    relpath,
                    lineno,
                    symbol,
                    f"dotted path '{dotted}' reads key '{key}' that "
                    f"bench section '{head}' never produces",
                )
            )

        for module in package.modules:
            for node in ast.walk(module.tree):
                s = _const_str(node)
                if s is None or not _DOTTED_RE.match(s):
                    continue
                check_path(
                    s, module.relpath, node.lineno, module.name,
                    module=module,
                )
        baseline = self._perf_baseline_path or sibling_path(
            package, "perf_baseline.json"
        )
        if baseline:
            try:
                with open(baseline, encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                data = {}
            entries = (
                data.get("metrics", data)
                if isinstance(data, dict)
                else {}
            )
            if isinstance(entries, dict):
                for name, entry in sorted(entries.items()):
                    if isinstance(entry, dict) and isinstance(
                        entry.get("path"), str
                    ):
                        check_path(
                            entry["path"],
                            "perf_baseline.json",
                            1,
                            f"<{name}>",
                        )
        return out

    # -- journal --------------------------------------------------------------

    def _journal_checks(
        self, package: Package, contract: Dict[str, Any]
    ) -> List[Finding]:
        spec = contract.get("journal_record")
        if not isinstance(spec, dict):
            return []
        out: List[Finding] = []
        for fn in package.functions:
            low = fn.qualname.lower()
            if "journal" not in low and "replay" not in low:
                continue
            env = _SpecEnv()
            for node in ast.walk(fn.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and self._is_json_loads(node.value)
                ):
                    env.tag(
                        node.targets[0].id, spec, "the journal record"
                    )
            if not env.specs:
                continue
            checker = _ReadChecker(self.rule, fn)
            checker.walk(env, fn.node)
            out.extend(checker.findings)
        return out
