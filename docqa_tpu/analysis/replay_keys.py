"""replay-key-integrity: no salted builtin ``hash()`` in keys that must
survive a restart.

CPython salts ``str``/``bytes`` hashes per process (PYTHONHASHSEED): the
same string hashes to a different value in every interpreter.  A builtin
``hash()`` that flows into a *cross-restart-persistent* key — prefix
cache keys (session-affinity routing), journal records (crash replay),
the recallscope shadow sampler (its cross-restart determinism claim),
baseline fingerprints — silently breaks every replay/affinity contract
while passing every single-process test.  Sanctioned derivations:
``hashlib``, ``zlib.crc32``, and pure-integer arithmetic (ints hash to
themselves, unsalted — the shadow sampler's multiply-and-mask scheme).

Scope: the modules that mint persistent keys (qa prefix keys, paged/pool
prefix-cache and affinity keys, serve routing, broker journal,
retrieval-observatory sampler, store fingerprints) — plus one resolve
hop: a helper *called from* a scope module owns its ``hash()`` site even
if it lives elsewhere (the finding names the reaching caller).  Fixtures
opt in with the ``docqa-lint: request-path`` pragma.

A ``hash()`` whose argument is provably numeric (int literal, ``int()``/
``len()``/``ord()`` result, arithmetic over those) is NOT flagged —
integer hashing is stable.  Anything else (names, strings, tuples)
flags: the safe rewrite is one line of hashlib.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    stmt_walk,
)

PERSIST_KEY_MODULES = frozenset(
    {
        "docqa_tpu.service.qa",
        "docqa_tpu.service.broker",
        "docqa_tpu.engines.serve",
        "docqa_tpu.engines.paged",
        "docqa_tpu.engines.pool",
        "docqa_tpu.obs.retrieval_observatory",
        "docqa_tpu.index.store",
    }
)

_NUMERIC_CALLS = frozenset({"int", "len", "ord", "round", "abs"})


def _provably_numeric(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, bool))
    if isinstance(node, ast.UnaryOp):
        return _provably_numeric(node.operand)
    if isinstance(node, ast.BinOp):
        return _provably_numeric(node.left) and _provably_numeric(
            node.right
        )
    if isinstance(node, ast.Call):
        name = call_name(node)
        return name in _NUMERIC_CALLS
    return False


class ReplayKeyChecker:
    rule = "replay-key-integrity"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        seen_sites: Set[Tuple[str, int]] = set()
        for fn in package.functions:
            module = fn.module
            if not (
                module.name in PERSIST_KEY_MODULES
                or module.request_path_pragma
            ):
                continue
            self._scan(package, fn, None, out, seen_sites, hop=0)
        for module in package.modules:
            if not (
                module.name in PERSIST_KEY_MODULES
                or module.request_path_pragma
            ):
                continue
            stack = list(ast.iter_child_nodes(module.tree))
            while stack:
                node = stack.pop()
                if isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                if isinstance(node, ast.Call):
                    self._check_hash(
                        module, node, "<module>", None, out, seen_sites
                    )
                stack.extend(ast.iter_child_nodes(node))
        return out

    def _scan(
        self,
        package: Package,
        fn: FunctionInfo,
        origin: FunctionInfo,
        out: List[Finding],
        seen: Set[Tuple[str, int]],
        hop: int,
    ) -> None:
        for node in stmt_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            self._check_hash(fn.module, node, fn.qualname, origin, out, seen)
            if hop == 0:
                callee = package.resolve_call(fn, node)
                if (
                    callee is not None
                    and callee.module.name not in PERSIST_KEY_MODULES
                ):
                    # one resolve hop: a helper a scope module delegates
                    # key construction to owns its hash() sites
                    self._scan(package, callee, fn, out, seen, hop=1)

    def _check_hash(
        self,
        module,
        node: ast.Call,
        symbol: str,
        origin,
        out: List[Finding],
        seen: Set[Tuple[str, int]],
    ) -> None:
        if call_name(node) != "hash" or "hash" in module.imports:
            return
        if len(node.args) != 1 or node.keywords:
            return
        if _provably_numeric(node.args[0]):
            return
        site = (module.relpath, getattr(node, "lineno", 1))
        if site in seen:
            return
        seen.add(site)
        reached = (
            f" (reached from {origin.module.name}.{origin.qualname})"
            if origin is not None
            else ""
        )
        out.append(
            Finding(
                self.rule,
                module.relpath,
                getattr(node, "lineno", 1),
                symbol,
                "builtin hash() feeding a cross-restart-persistent key"
                f"{reached} — str/bytes hashes are salted per process "
                "(PYTHONHASHSEED), so the key differs every restart; "
                "derive with hashlib/zlib.crc32 or pure-integer "
                "arithmetic",
            )
        )
