"""thread-lifecycle: every thread has a reachable join on its owner's
stop/close path.

Two abort classes this rule exists for, both hit twice in PRs 6–7:

* **fire-and-forget compile threads** — a daemon thread still inside an
  XLA compile (or holding a live sharded dispatch) when the interpreter
  exits aborts the process (``std::terminate`` out of the PJRT client).
  The pool learned to track and join its rebuild warmups; the runtime
  learned to join its boot warmup.  This rule makes the lesson a gate:
  a ``threading.Thread`` whose target's call graph (the same package
  call resolution jit-purity closes over) can reach a jax dispatch MUST
  be join-reachable, daemon or not;
* **leaked workers** — a non-daemon thread with no join anywhere keeps
  the process alive on shutdown; a daemon one dies mid-mutation.

"Join-reachable" is checked in the thread's OWNER scope:

* ``self._x = threading.Thread(…)`` — some method of the same module
  joins ``self._x`` (directly, or through a local alias
  ``t = self._x; t.join(…)``);
* a local ``t = threading.Thread(…)`` — the same function joins ``t``,
  or ``t`` flows into a container (``append``, list literal, list
  concat) that a ``for`` loop later iterates and joins;
* ``threading.Thread(…).start()`` with NO binding can never be joined —
  always flagged (the ivf-rebuild idiom this PR fixes).

Deliberately unjoined threads (a watchdog designed to die with the
process and provably free of device work) belong in the baseline with a
justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.concurrency import (
    dispatch_reachable,
    enumerate_thread_entries,
)
from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)


def _join_receivers(root: ast.AST) -> Set[str]:
    """Dotted receiver texts of every ``.join(…)`` call under ``root``."""
    out: Set[str] = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "join":
            recv = dotted_name(node.func.value)
            if recv:
                out.add(recv)
    return out


def _local_aliases_of(root: ast.AST, attr: str) -> Set[str]:
    """Local names assigned from ``self.<attr>`` — plain reads and the
    defensive ``getattr(self, "<attr>", None)`` idiom alike."""
    out: Set[str] = set()
    for node in ast.walk(root):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        matches = (
            isinstance(value, ast.Attribute) and value.attr == attr
        )
        if (
            not matches
            and isinstance(value, ast.Call)
            and call_name(value) == "getattr"
            and len(value.args) >= 2
            and isinstance(value.args[1], ast.Constant)
            and value.args[1].value == attr
        ):
            matches = True
        if matches:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _containers_fed_by(root: ast.AST, name: str) -> Set[str]:
    """Container expressions (dotted text) the name flows into: via
    ``c.append(name)``, ``c = [... name ...]`` list literals, or list
    concatenation re-assignments (the pool's ``self._warmups = […] + [t]``
    idiom)."""
    out: Set[str] = set()
    for node in ast.walk(root):
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr == "append":
            if any(
                isinstance(a, ast.Name) and a.id == name for a in node.args
            ):
                recv = dotted_name(node.func.value)
                if recv:
                    out.add(recv)
        elif isinstance(node, ast.Assign):
            has_name = any(
                isinstance(n, ast.Name) and n.id == name
                for n in ast.walk(node.value)
            )
            if not has_name:
                continue
            for t in node.targets:
                text = dotted_name(t)
                if text:
                    out.add(text)
    return out


def _loop_vars_over(root: ast.AST, containers: Set[str]) -> Set[str]:
    """Loop variables of ``for v in <container>`` statements."""
    out: Set[str] = set()
    norm = {c.split(".")[-1] for c in containers} | containers
    for node in ast.walk(root):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            it = dotted_name(node.iter)
            if not it and isinstance(node.iter, ast.Call):
                # for t in list(self._warmups): / reversed(threads):
                if node.iter.args:
                    it = dotted_name(node.iter.args[0])
            if not it and isinstance(node.iter, (ast.Tuple, ast.List)):
                # for t in (sampler, watchdog_thread): — the loop var
                # aliases each named element
                if any(
                    dotted_name(e) in containers
                    or dotted_name(e).split(".")[-1] in norm
                    for e in node.iter.elts
                    if dotted_name(e)
                ):
                    out.add(node.target.id)
                    continue
            if it and (it in containers or it.split(".")[-1] in norm):
                out.add(node.target.id)
    return out


class ThreadLifecycleChecker:
    rule = "thread-lifecycle"

    def check(self, package: Package) -> List[Finding]:
        reach = dispatch_reachable(package)
        out: List[Finding] = []

        # module-wide join receivers, computed once per module
        module_joins: Dict[object, Set[str]] = {}

        for entry in enumerate_thread_entries(package):
            if entry.kind != "thread":
                continue  # executor lanes belong to dispatch-streams
            fn = self._site_fn(package, entry)
            if fn is None:
                continue
            module = fn.module
            binding = self._binding(fn, entry.lineno)
            joined = self._is_joined(
                package, fn, module, binding, module_joins
            )
            if joined:
                continue
            target_reach = (
                reach.get(id(entry.target.node))
                if entry.target is not None
                else None
            )
            name = entry.thread_name or entry.target_text or "<thread>"
            if target_reach is not None:
                detail = (
                    f" and its target can reach a jax dispatch "
                    f"({target_reach}): a live XLA compile on an "
                    "unjoined thread at interpreter exit aborts the "
                    "process"
                )
            elif entry.daemon:
                detail = (
                    ": a daemon thread dies mid-mutation at interpreter "
                    "exit"
                )
            else:
                detail = ": an unjoined non-daemon thread blocks shutdown"
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    entry.lineno,
                    entry.site_qualname,
                    f"thread {name!r} has no reachable join() on its "
                    f"owner's stop/close path{detail}",
                )
            )
        return out

    # -- helpers --------------------------------------------------------------

    def _site_fn(self, package: Package, entry) -> Optional[FunctionInfo]:
        for fn in package.functions:
            if (
                fn.module.relpath == entry.module_relpath
                and fn.qualname == entry.site_qualname
            ):
                return fn
        if entry.site_qualname == "<module>":
            from docqa_tpu.analysis.concurrency import module_scope_fn

            for m in package.modules:
                if m.relpath == entry.module_relpath:
                    return module_scope_fn(m)
        return None

    def _binding(self, fn: FunctionInfo, lineno: int) -> Optional[str]:
        """The name the Thread(...) at ``lineno`` is bound to: 'self.X',
        a local name, a container it is appended into — or None for an
        unbound ``Thread(...).start()`` chain."""

        def creates_here(root: ast.AST) -> bool:
            return any(
                isinstance(c, ast.Call)
                and c.lineno == lineno
                and call_name(c).rsplit(".", 1)[-1] == "Thread"
                for c in ast.walk(root)
            )

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and creates_here(node.value):
                for t in node.targets:
                    text = dotted_name(t)
                    if text:
                        return text
            # threads.append(Thread(...)): bound to the container
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
                and node.args
                and creates_here(node.args[0])
            ):
                recv = dotted_name(node.func.value)
                if recv:
                    return recv
        return None

    def _is_joined(
        self,
        package: Package,
        fn: FunctionInfo,
        module,
        binding: Optional[str],
        module_joins: Dict[object, Set[str]],
    ) -> bool:
        if binding is None:
            return False  # Thread(...).start() — nothing to join
        if module not in module_joins:
            module_joins[module] = _join_receivers(module.tree)
        joins = module_joins[module]

        def attr_joined(attr: str) -> bool:
            """self.X joined anywhere in the module: `self.X.join`, an
            alias `t = self.X; t.join` (getattr idiom included), or via
            a joined for-loop over a container self.X flows into."""
            if any(j.split(".")[-1] == attr for j in joins):
                return True
            for other in package.functions:
                if other.module is not module:
                    continue
                local_joins = _join_receivers(other.node)
                for alias in _local_aliases_of(other.node, attr):
                    if alias in local_joins:
                        return True
                # for t in self.X: t.join(...)
                loop_vars = _loop_vars_over(other.node, {f"self.{attr}"})
                if loop_vars & local_joins:
                    return True
            return False

        if binding.startswith("self."):
            attr = binding.split(".", 1)[1]
            if attr_joined(attr):
                return True
            # the thread may flow onward into a tracked container
            containers = _containers_fed_by(module.tree, attr)
            return any(
                attr_joined(c.split(".")[-1]) for c in containers
            )

        # local binding: joined in the same function, or flows into a
        # container / self attribute that is joined elsewhere
        local_joins = _join_receivers(fn.node)
        if binding in local_joins:
            return True
        # the binding may itself BE the container (threads = [Thread(…),
        # …] at script scope) — treat it as one for the loop-join scan
        containers = {binding} | _containers_fed_by(fn.node, binding)
        loop_vars = _loop_vars_over(fn.node, containers)
        if loop_vars & local_joins:
            return True
        for c in containers:
            if c.startswith("self.") and attr_joined(c.split(".", 1)[1]):
                return True
        # module-level script idiom: threads list at module scope
        mod_loop_vars = _loop_vars_over(module.tree, containers)
        return bool(mod_loop_vars & _join_receivers(module.tree))
