"""host-sync: no blocking device→host syncs on the /ask serving path
outside jit-traced code.

jit-purity polices host syncs INSIDE traced code (they break tracing);
this rule covers the blind spot it deliberately leaves: plain host
functions on the request path.  There, ``np.asarray``/``.item()``/
``jax.device_get``/``float(device_value)`` are legal Python — and each
one BLOCKS the calling thread until the device pipeline drains
(docs/PERF.md §1: ~66 ms per sync on the tunneled chip).  The serving
loop's whole design is ONE packed fetch per decode chunk
(``serve._process_chunk``) with everything else chained device-side; a
stray scalar sync re-serializes the pipeline invisibly.

Scope: the /ask chain (``deadline_flow.REQUEST_PATH_MODULES``; fixtures
opt in with ``# docqa-lint: request-path``), minus every function the
jit-purity discovery marks traced (those belong to that rule).

Findings — patterns that are *unambiguously* a sync; the sanctioned
fetch idiom (``host = np.asarray(device_ref)`` on a name/attribute, one
per dispatch) is deliberately NOT flagged:

1. ``jax.device_get(...)`` — a fetch by definition;
2. ``.item()`` / ``.tolist()`` — scalar/list syncs (host containers have
   no ``.item``; a numpy receiver would already be host-side and cheap,
   so the conservative flag is still actionable);
3. ``float(x)`` / ``int(x)`` / ``bool(x)`` where ``x``'s fact says
   device: assigned from a ``jnp.*``/``jax.*`` call or from calling a
   known jit wrapper (a local ``fn = jax.jit(...)`` / the engines'
   ``_get_*_fn()`` accessors);
4. ``np.asarray(...)`` / ``np.array(...)`` applied DIRECTLY to a
   ``jnp``/``jax`` call or a jit-wrapper call — materializing a freshly
   computed device intermediate on the host mid-pipeline, instead of the
   fetch-a-held-reference idiom.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
)
from docqa_tpu.analysis.deadline_flow import REQUEST_PATH_MODULES
from docqa_tpu.analysis.jit_purity import (
    JIT_WRAPPERS,
    JitPurityChecker,
    discover_jit_roots,
)

_GET_FN_RE = re.compile(r"_get_\w*fn$")
_SYNC_METHODS = frozenset({"item", "tolist"})


def traced_function_ids(package: Package) -> Set[int]:
    """ids of every function node jit-purity considers traced (direct
    roots + transitive closure over package calls) — host-sync must not
    double-report inside them."""
    checker = JitPurityChecker()
    traced, lambdas = discover_jit_roots(package)
    frontier = [(fn, fn.node) for fn, _via in traced.values()]
    frontier.extend((fn, lam) for fn, lam, _via in lambdas)
    while frontier:
        fn, body = frontier.pop()
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = fn.module.resolve_alias(name).rsplit(".", 1)[-1]
            if tail in JIT_WRAPPERS:
                continue
            callee = package.resolve_call(fn, node)
            if callee is None and name and "." not in name:
                callee = checker._partial_alias(package, fn, name)
            if callee is not None and id(callee.node) not in traced:
                traced[id(callee.node)] = (callee, "")
                frontier.append((callee, callee.node))
    return set(traced)


class HostSyncChecker:
    rule = "host-sync"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        traced = traced_function_ids(package)
        for fn in package.functions:
            module = fn.module
            if not (
                module.name in REQUEST_PATH_MODULES
                or module.request_path_pragma
            ):
                continue
            if id(fn.node) in traced:
                continue
            self._scan(fn, out)
        return out

    # -- per-function --------------------------------------------------------

    def _scan(self, fn: FunctionInfo, out: List[Finding]) -> None:
        module = fn.module

        def add(node, message) -> None:
            out.append(
                Finding(
                    self.rule, module.relpath,
                    getattr(node, "lineno", 1), fn.qualname, message,
                )
            )

        # device facts: name -> True when the value lives on device
        device: Dict[str, bool] = {}
        # names bound to jit wrappers (calling them yields device values)
        wrappers: Set[str] = set()

        def is_device_call(call: ast.Call) -> bool:
            name = call_name(call)
            if not name:
                return False
            resolved = module.resolve_alias(name)
            head = resolved.split(".")[0]
            tail = resolved.rsplit(".", 1)[-1]
            if head in ("jnp",) or resolved.startswith("jax.numpy."):
                return True
            if resolved.startswith("jax.lax.") or resolved.startswith(
                "jax.random."
            ):
                return True
            if tail in JIT_WRAPPERS:
                return False  # constructing a wrapper is not a dispatch
            base = name.split(".")[0]
            if base in wrappers or name in wrappers:
                return True
            return False

        def expr_is_device(node: ast.AST) -> bool:
            if isinstance(node, ast.Name):
                return device.get(node.id, False)
            if isinstance(node, ast.Subscript):
                return expr_is_device(node.value)
            if isinstance(node, ast.Attribute) and node.attr in (
                "T", "mT", "real", "imag"
            ):
                return expr_is_device(node.value)
            if isinstance(node, ast.Call):
                name = call_name(node)
                resolved = module.resolve_alias(name) if name else ""
                # np.asarray(...) LAUNDERS: its result is host-side
                if resolved.rsplit(".", 1)[-1] in (
                    "asarray", "array"
                ) and resolved.split(".")[0] in ("np", "numpy"):
                    return False
                return is_device_call(node)
            if isinstance(node, ast.BinOp):
                return expr_is_device(node.left) or expr_is_device(
                    node.right
                )
            return False

        def handle_expr(node: ast.AST) -> None:
            """Check every call in an expression tree, without descending
            into nested defs/lambdas (their own scopes)."""
            stack = [node]
            while stack:
                cur = stack.pop()
                if isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(cur, ast.Call):
                    check_call(cur)
                stack.extend(ast.iter_child_nodes(cur))

        def bind_assign(stmt: ast.Assign) -> None:
            value = stmt.value
            jitish = False
            if isinstance(value, ast.Call):
                name = call_name(value)
                tail = (
                    module.resolve_alias(name).rsplit(".", 1)[-1]
                    if name else ""
                )
                attr_tail = name.rsplit(".", 1)[-1] if name else ""
                jitish = tail in JIT_WRAPPERS or bool(
                    _GET_FN_RE.search(attr_tail)
                )
            dev = expr_is_device(value)
            for target in stmt.targets:
                for n in ast.walk(target):
                    if not isinstance(n, ast.Name):
                        continue
                    if jitish:
                        wrappers.add(n.id)
                        device[n.id] = False
                    else:
                        device[n.id] = dev

        # statement-order scan (no nested defs: they have their own pass)
        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if isinstance(stmt, ast.Assign):
                    handle_expr(stmt.value)
                    bind_assign(stmt)
                    continue
                for _name, field in ast.iter_fields(stmt):
                    if isinstance(field, ast.expr):
                        handle_expr(field)
                    elif isinstance(field, list):
                        if field and isinstance(field[0], ast.stmt):
                            walk(field)
                        elif field and isinstance(
                            field[0], ast.excepthandler
                        ):
                            for handler in field:
                                walk(handler.body)
                        elif field and isinstance(field[0], ast.expr):
                            for e in field:
                                handle_expr(e)
                        elif field and isinstance(field[0], ast.withitem):
                            for item in field:
                                handle_expr(item.context_expr)

        def check_call(node: ast.Call) -> None:
            name = call_name(node)
            if not name:
                return
            resolved = module.resolve_alias(name)
            tail = name.rsplit(".", 1)[-1]
            if resolved == "jax.device_get":
                add(node, "jax.device_get() on the request path — a "
                         "blocking device fetch outside the sanctioned "
                         "one-fetch-per-dispatch idiom")
                return
            if tail in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
                add(node, f".{tail}() on the request path — a blocking "
                         "scalar/host sync; batch it into the dispatch's "
                         "single packed fetch")
                return
            if name in ("float", "int", "bool") and len(node.args) == 1:
                if expr_is_device(node.args[0]):
                    add(node, f"{name}() on a device value — an implicit "
                             "blocking sync per scalar; fetch once with "
                             "np.asarray and convert host-side")
                return
            if tail in ("asarray", "array") and resolved.split(".")[0] in (
                "np", "numpy"
            ):
                if node.args and isinstance(node.args[0], ast.Call) and (
                    is_device_call(node.args[0])
                ):
                    add(node, "np.asarray() directly over a device "
                             "computation — materializes a mid-pipeline "
                             "intermediate on host; keep the value "
                             "device-side or fetch a held reference once")

        body = getattr(fn.node, "body", None)
        if body:
            walk(body)
