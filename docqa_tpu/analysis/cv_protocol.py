"""cv-protocol: condition variables used the one correct way.

Three sub-rules over every ``threading.Condition`` (discovered
assignments, dataclass ``field(default_factory=…)`` declarations, and
cv-ish receivers — ``…._cv`` / ``….cv``):

1. **wait-in-a-loop** — ``cv.wait(…)`` must sit inside a ``while`` whose
   predicate is re-checked after every wakeup.  Spurious wakeups and
   stolen predicates are not theoretical: ``notify_all`` wakes every
   waiter and only one gets the queue slot.  An ``if``-guarded or bare
   wait flags; ``wait_for`` carries its own predicate loop and is
   exempt.
2. **notify-under-the-lock** — ``cv.notify()`` / ``notify_all()``
   without holding the cv (or the lock it was constructed over —
   ``Condition(self._lock)`` aliases canonicalize) raises RuntimeError
   at runtime *when it runs*; the paths that notify on error cleanup
   are exactly the ones tests never run.  A helper whose every
   package-resolvable call site holds the cv is analyzed as holding it
   (``serve._pop_free_slots`` — "caller holds ``_cv``").
3. **request-path waits carry a Deadline** — in the ``/ask`` serving
   chain (``deadline_flow.REQUEST_PATH_MODULES``, which now includes
   ``engines.pool``), a ``cv.wait`` whose timeout is neither derived
   from a deadline (``.bound(…)`` / ``.remaining(…)`` dataflow, same
   derivation deadline-flow uses) nor clamped by one in scope is a wait
   that can outlive the request budget.  Composes with deadline-flow:
   that rule flags unclamped waits *when a deadline is in scope*; this
   one flags request-path cv waits with NO deadline in reach at all —
   the worker's idle tick is the known, baselined exception.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.concurrency import (
    CONDITIONISH_ATTR_RE,
    canonical,
    discover_locks,
    held_at_call_sites,
    is_lock_expr,
    known_lock_attrs,
    lock_aliases,
    lock_id_for,
)
from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
)
from docqa_tpu.analysis.deadline_flow import (
    REQUEST_PATH_MODULES,
    _FunctionScan,
)


def _is_cvish(receiver: str, known_cvs: Set[str]) -> bool:
    if not receiver:
        return False
    attr = receiver.rsplit(".", 1)[-1]
    return attr in known_cvs or bool(CONDITIONISH_ATTR_RE.search(attr))


class CvProtocolChecker:
    rule = "cv-protocol"

    def check(self, package: Package) -> List[Finding]:
        decls = discover_locks(package)
        aliases = lock_aliases(decls)
        known_attrs = known_lock_attrs(decls)
        known_cvs = {
            d.lock_id.rsplit(".", 1)[-1]
            for d in decls.values()
            if d.kind == "Condition"
        }
        call_site_held = held_at_call_sites(package, known_attrs)
        out: List[Finding] = []
        for fn in package.functions:
            out.extend(
                self._check_fn(
                    fn, known_attrs, known_cvs, aliases, call_site_held
                )
            )
        return out

    def _check_fn(
        self,
        fn: FunctionInfo,
        known_attrs: Set[str],
        known_cvs: Set[str],
        aliases: Dict[str, str],
        call_site_held: Dict[int, Set[str]],
    ) -> List[Finding]:
        module = fn.module
        request_path = (
            module.name in REQUEST_PATH_MODULES or module.request_path_pragma
        )
        base_held = {
            canonical(lid, aliases)
            for lid in call_site_held.get(id(fn.node), set())
        }
        scan: Optional[_FunctionScan] = None
        out: List[Finding] = []

        def visit(
            node: ast.AST, held: Tuple[str, ...], in_while: bool
        ) -> None:
            nonlocal scan
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                child_in_while = in_while or isinstance(child, ast.While)
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if isinstance(item.context_expr, ast.Call):
                            continue
                        try:
                            text = ast.unparse(item.context_expr)
                        except Exception:
                            continue
                        if is_lock_expr(text, known_attrs) or _is_cvish(
                            text, known_cvs
                        ):
                            new_held = new_held + (
                                canonical(
                                    lock_id_for(fn, text), aliases
                                ),
                            )
                if isinstance(child, ast.Call):
                    name = call_name(child)
                    attr = name.rsplit(".", 1)[-1] if name else ""
                    receiver = (
                        name.rsplit(".", 1)[0] if "." in name else ""
                    )
                    if attr in ("wait", "notify", "notify_all") and _is_cvish(
                        receiver, known_cvs
                    ):
                        cv_id = canonical(
                            lock_id_for(fn, receiver), aliases
                        )
                        holds = cv_id in set(new_held) | base_held
                        if attr == "wait":
                            if not child_in_while:
                                out.append(
                                    Finding(
                                        self.rule,
                                        module.relpath,
                                        child.lineno,
                                        fn.qualname,
                                        f"{receiver}.wait() outside a "
                                        "while-predicate loop (spurious "
                                        "wakeups and stolen predicates "
                                        "need the re-check; use wait_for "
                                        "or loop)",
                                    )
                                )
                            if request_path:
                                if scan is None:
                                    scan = _FunctionScan(fn)
                                arg = scan.timeout_arg(child, "wait")
                                clamped = (
                                    arg is not None
                                    and scan.arg_is_clamped(arg)
                                )
                                if not scan.has_deadline() and not clamped:
                                    out.append(
                                        Finding(
                                            self.rule,
                                            module.relpath,
                                            child.lineno,
                                            fn.qualname,
                                            f"request-path {receiver}."
                                            "wait() without a Deadline: "
                                            "the timeout is neither "
                                            "deadline-derived nor is one "
                                            "in scope to clamp it",
                                        )
                                    )
                        else:  # notify / notify_all
                            if not holds:
                                out.append(
                                    Finding(
                                        self.rule,
                                        module.relpath,
                                        child.lineno,
                                        fn.qualname,
                                        f"{receiver}.{attr}() without "
                                        f"holding {cv_id} — notify "
                                        "outside the lock raises "
                                        "RuntimeError on exactly the "
                                        "paths tests never run",
                                    )
                                )
                visit(child, new_held, child_in_while)

        visit(fn.node, (), False)
        return out
