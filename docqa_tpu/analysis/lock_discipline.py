"""lock-discipline: consistent acquisition order, no blocking I/O held.

The serving process holds ~a dozen ``threading.Lock``/``Condition``
instances (batcher CV, broker CV, store RLock, registry lock, pipeline
suppress lock, tiered rebuild lock, metrics locks).  Two classes of bug
regress silently:

* **inconsistent ordering** — thread 1 acquires A then B, thread 2
  acquires B then A: a deadlock that only fires under load.  The checker
  discovers lock attributes (``self.X = threading.Lock()/RLock()/
  Condition()``, plus module-level ones), builds the acquisition graph
  (edges from every held lock to each lock acquired under it, including
  one level through package-resolvable calls), and flags every 2-cycle.
  Lock identity is ``Class.attr`` for ``self`` attributes and the
  receiver text otherwise — an approximation without types, so two
  *instances* of one class's lock are one node (conservative: flags the
  pattern, which is what ordering discipline is about).
* **blocking while holding a lock** — broker publishes, journal fsyncs,
  registry/DB writes, checkpoint loads, thread joins, sleeps, decode
  waits performed inside a critical section stall every other thread
  contending for that lock.  Blocking-ness propagates through
  package-resolvable calls (``publish`` under a lock is flagged even when
  the fsync lives two calls down).  ``cv.wait(…)`` on the *held*
  condition is the one legitimate blocking-under-lock (it releases), and
  is exempt.

Both sub-rules are per-site findings; deliberate exceptions (e.g. the
broker's journal write, whose ordering IS the lock's job) belong in the
baseline with a justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    stmt_walk as _stmt_walk,
)

LOCK_FACTORY_RE = re.compile(
    r"threading\.(?:Lock|RLock|Condition)\b|multiprocessing\.Lock\b"
)
LOCKISH_ATTR_RE = re.compile(r"(?:^|_)(?:lock|cv|mutex|rlock)$|_lock$|_cv$")

# Attribute names whose calls block the calling thread.  Deliberately
# curated for this codebase (broker publishes, registry writes, journal
# fsync, decode waits); generic DB cursor traffic (``execute``/``commit``)
# is excluded — the registry's lock exists precisely to serialize its
# connection, and flagging its own design would be noise.
BLOCKING_ATTRS = frozenset(
    {
        "sleep",
        "publish",
        "get_many",
        "communicate",
        "urlopen",
        "fsync",
        "result",
        "drain",
        "wait",
        "set_status",
        "set_status_unless_deleted",
        "list_documents",
        "encode_texts",
        "deidentify_batch",
        "extract_text_ex",
        "load_checkpoint_dir",
    }
)

# ``.join`` is blocking only on thread-like receivers — ``str.join`` /
# ``os.path.join`` share the attribute name.
THREADISH_RE = re.compile(r"worker|thread|proc|consumer", re.IGNORECASE)


def _is_blocking_call(module, node: ast.Call) -> Optional[str]:
    """Blocking description for this call, or None."""
    name = call_name(node)
    if not name:
        return None
    attr = name.rsplit(".", 1)[-1]
    receiver = name.rsplit(".", 1)[0] if "." in name else ""
    resolved = module.resolve_alias(name)
    if attr in BLOCKING_ATTRS:
        return name
    if resolved == "time.sleep" or resolved == "os.fsync":
        return resolved
    if attr == "join" and (
        THREADISH_RE.search(receiver)
        or any(kw.arg == "timeout" for kw in node.keywords)
    ):
        return name
    return None


class LockDisciplineChecker:
    rule = "lock-discipline"

    # -- lock discovery -------------------------------------------------------

    def _discover_locks(self, package: Package) -> Set[str]:
        """Attribute/variable names assigned a threading primitive."""
        names: Set[str] = set()
        for module in package.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = getattr(node, "value", None)
                if value is None:
                    continue
                text = ""
                try:
                    text = ast.unparse(value)
                except Exception:
                    pass
                if not LOCK_FACTORY_RE.search(text):
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        names.add(t.attr)
                    elif isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    def _lock_id(
        self, fn: FunctionInfo, expr_text: str
    ) -> str:
        """Stable identity: Class.attr for self attrs, receiver text else."""
        attr = expr_text.rsplit(".", 1)[-1]
        if expr_text.startswith("self.") and fn.class_name:
            return f"{fn.class_name}.{attr}"
        return expr_text

    def _is_lock_expr(self, text: str, known: Set[str]) -> bool:
        if not text:
            return False
        attr = text.rsplit(".", 1)[-1]
        return attr in known or bool(LOCKISH_ATTR_RE.search(attr))

    # -- blocking propagation -------------------------------------------------

    def _direct_blocking(
        self, fn: FunctionInfo
    ) -> List[Tuple[ast.Call, str]]:
        out = []
        for node in _stmt_walk(fn.node):
            if isinstance(node, ast.Call):
                desc = _is_blocking_call(fn.module, node)
                if desc is not None:
                    out.append((node, desc))
        return out

    def _blocking_closure(
        self, package: Package
    ) -> Dict[int, Set[str]]:
        """fn-node-id -> set of blocking descriptions reachable from it."""
        blocking: Dict[int, Set[str]] = {}
        for fn in package.functions:
            direct = {
                name for _node, name in self._direct_blocking(fn)
            }
            if direct:
                blocking[id(fn.node)] = direct
        changed = True
        while changed:
            changed = False
            for fn in package.functions:
                for node in _stmt_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = package.resolve_call(fn, node)
                    if callee is None:
                        continue
                    sub = blocking.get(id(callee.node))
                    if not sub:
                        continue
                    cur = blocking.setdefault(id(fn.node), set())
                    # propagate the callee NAME only (bounded strings)
                    tag = f"{call_name(node)}()"
                    if tag not in cur:
                        cur.add(tag)
                        changed = True
        return blocking

    # -- main -----------------------------------------------------------------

    def check(self, package: Package) -> List[Finding]:
        known_locks = self._discover_locks(package)
        blocking = self._blocking_closure(package)
        out: List[Finding] = []
        # acquisition-order edges: (A, B) -> first example site
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        for fn in package.functions:
            self._check_fn(package, fn, known_locks, blocking, edges, out)

        # 2-cycles in the acquisition graph
        reported: Set[frozenset] = set()
        for (a, b), (path, line, sym) in sorted(edges.items()):
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                p2, l2, s2 = edges[(b, a)]
                out.append(
                    Finding(
                        self.rule,
                        path,
                        line,
                        sym,
                        f"inconsistent lock order: {a} -> {b} here but "
                        f"{b} -> {a} in {s2} ({p2}:{l2})",
                    )
                )
        return out

    def _check_fn(
        self,
        package: Package,
        fn: FunctionInfo,
        known_locks: Set[str],
        blocking: Dict[int, Set[str]],
        edges: Dict,
        out: List[Finding],
    ) -> None:
        module = fn.module

        def visit(node: ast.AST, held: List[Tuple[str, str]]) -> None:
            # held: list of (lock_id, receiver_text)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    acquired: List[Tuple[str, str]] = []
                    for item in child.items:
                        try:
                            text = ast.unparse(item.context_expr)
                        except Exception:
                            text = ""
                        if isinstance(item.context_expr, ast.Call):
                            continue  # with span(...), with open(...) ...
                        if self._is_lock_expr(text, known_locks):
                            lock = self._lock_id(fn, text)
                            # edges from every already-held lock AND from
                            # earlier items of this same with-statement
                            # (`with a, b:` acquires a then b — the
                            # canonical deadlock pair against
                            # `with b: with a:` elsewhere)
                            for h, _r in held + acquired:
                                if h != lock:
                                    edges.setdefault(
                                        (h, lock),
                                        (module.relpath, child.lineno,
                                         fn.qualname),
                                    )
                            acquired.append((lock, text))
                    visit(child, held + acquired)
                    continue
                if isinstance(child, ast.Call) and held:
                    name = call_name(child)
                    attr = name.rsplit(".", 1)[-1] if name else ""
                    receiver = name.rsplit(".", 1)[0] if "." in name else ""
                    held_receivers = {r for _h, r in held}
                    if attr in ("wait", "notify", "notify_all") and (
                        receiver in held_receivers
                    ):
                        pass  # cv ops on the held lock are the pattern
                    elif _is_blocking_call(module, child) is not None:
                        out.append(
                            Finding(
                                self.rule,
                                module.relpath,
                                child.lineno,
                                fn.qualname,
                                f"blocking call {name}() while holding "
                                f"{held[-1][0]}",
                            )
                        )
                    else:
                        callee = package.resolve_call(fn, child)
                        if callee is not None:
                            sub = blocking.get(id(callee.node))
                            if sub:
                                out.append(
                                    Finding(
                                        self.rule,
                                        module.relpath,
                                        child.lineno,
                                        fn.qualname,
                                        f"call {name}() blocks (via "
                                        f"{sorted(sub)[0]}) while holding "
                                        f"{held[-1][0]}",
                                    )
                                )
                            # cross-call lock-order edges
                            for lock in self._locks_acquired(
                                callee, known_locks
                            ):
                                for h, _r in held:
                                    if h != lock:
                                        edges.setdefault(
                                            (h, lock),
                                            (
                                                module.relpath,
                                                child.lineno,
                                                fn.qualname,
                                            ),
                                        )
                visit(child, held)

        visit(fn.node, [])

    def _locks_acquired(
        self, fn: FunctionInfo, known_locks: Set[str]
    ) -> Set[str]:
        out: Set[str] = set()
        for node in _stmt_walk(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        continue
                    try:
                        text = ast.unparse(item.context_expr)
                    except Exception:
                        continue
                    if self._is_lock_expr(text, known_locks):
                        out.add(self._lock_id(fn, text))
        return out
