"""lock-discipline: consistent acquisition order, no blocking I/O held.

The serving process holds ~a dozen ``threading.Lock``/``Condition``
instances (batcher CV, broker CV, store RLock, registry lock, pipeline
suppress lock, tiered rebuild lock, metrics locks).  Two classes of bug
regress silently:

* **inconsistent ordering** — thread 1 acquires A then B, thread 2
  acquires B then A: a deadlock that only fires under load.  The checker
  discovers lock attributes (``self.X = threading.Lock()/RLock()/
  Condition()``, plus module-level ones), builds the acquisition graph
  (edges from every held lock to each lock acquired under it, through
  the TRANSITIVE closure of package-resolvable calls — a helper that
  takes a lock three frames down still orders against whatever its
  caller holds), and flags every cycle via full DFS (the original
  2-cycle-only scan missed any A→B→C→A inversion by construction; the
  dynamic witness in ``analysis/race_witness.py`` cross-checks its
  *witnessed* edges against exactly this graph, so the two views use one
  edge and one cycle definition).  ``Condition(self._lock)`` aliases
  canonicalize to the underlying lock — an "edge" between a cv and the
  lock it wraps is not an ordering fact.  Lock identity is
  ``Class.attr`` for ``self`` attributes and the receiver text
  otherwise — an approximation without types, so two *instances* of one
  class's lock are one node (conservative: flags the pattern, which is
  what ordering discipline is about).
* **blocking while holding a lock** — broker publishes, journal fsyncs,
  registry/DB writes, checkpoint loads, thread joins, sleeps, decode
  waits performed inside a critical section stall every other thread
  contending for that lock.  Blocking-ness propagates through
  package-resolvable calls (``publish`` under a lock is flagged even when
  the fsync lives two calls down).  ``cv.wait(…)`` on the *held*
  condition is the one legitimate blocking-under-lock (it releases), and
  is exempt.

Both sub-rules are per-site findings; deliberate exceptions (e.g. the
broker's journal write, whose ordering IS the lock's job) belong in the
baseline with a justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    stmt_walk as _stmt_walk,
)
from docqa_tpu.analysis.concurrency import (
    LOCKISH_ATTR_RE,
    canonical,
    discover_lock_attr_names,
    discover_locks,
    find_cycles,
    known_lock_attrs,
    lock_aliases,
)

# Attribute names whose calls block the calling thread.  Deliberately
# curated for this codebase (broker publishes, registry writes, journal
# fsync, decode waits); generic DB cursor traffic (``execute``/``commit``)
# is excluded — the registry's lock exists precisely to serialize its
# connection, and flagging its own design would be noise.
BLOCKING_ATTRS = frozenset(
    {
        "sleep",
        "publish",
        "get_many",
        "communicate",
        "urlopen",
        "fsync",
        "result",
        "drain",
        "wait",
        "set_status",
        "set_status_unless_deleted",
        "list_documents",
        "encode_texts",
        "deidentify_batch",
        "extract_text_ex",
        "load_checkpoint_dir",
    }
)

# ``.join`` is blocking only on thread-like receivers — ``str.join`` /
# ``os.path.join`` share the attribute name.
THREADISH_RE = re.compile(r"worker|thread|proc|consumer", re.IGNORECASE)


def _is_blocking_call(module, node: ast.Call) -> Optional[str]:
    """Blocking description for this call, or None."""
    name = call_name(node)
    if not name:
        return None
    attr = name.rsplit(".", 1)[-1]
    receiver = name.rsplit(".", 1)[0] if "." in name else ""
    resolved = module.resolve_alias(name)
    if attr in BLOCKING_ATTRS:
        return name
    if resolved == "time.sleep" or resolved == "os.fsync":
        return resolved
    if attr == "join" and (
        THREADISH_RE.search(receiver)
        or any(kw.arg == "timeout" for kw in node.keywords)
    ):
        return name
    return None


class LockDisciplineChecker:
    rule = "lock-discipline"

    # -- lock discovery -------------------------------------------------------

    def _discover_locks(self, package: Package) -> Set[str]:
        """Attribute/variable names assigned a threading primitive —
        delegated to the shared concurrency model (one regex, one
        implementation) so this classification can never drift from the
        witness id-map."""
        return discover_lock_attr_names(package)

    def _lock_id(
        self, fn: FunctionInfo, expr_text: str
    ) -> str:
        """Stable identity: Class.attr for self attrs, receiver text else."""
        attr = expr_text.rsplit(".", 1)[-1]
        if expr_text.startswith("self.") and fn.class_name:
            return f"{fn.class_name}.{attr}"
        return expr_text

    def _is_lock_expr(self, text: str, known: Set[str]) -> bool:
        if not text:
            return False
        attr = text.rsplit(".", 1)[-1]
        return attr in known or bool(LOCKISH_ATTR_RE.search(attr))

    # -- blocking propagation -------------------------------------------------

    def _direct_blocking(
        self, fn: FunctionInfo
    ) -> List[Tuple[ast.Call, str]]:
        out = []
        for node in _stmt_walk(fn.node):
            if isinstance(node, ast.Call):
                desc = _is_blocking_call(fn.module, node)
                if desc is not None:
                    out.append((node, desc))
        return out

    def _blocking_closure(
        self, package: Package
    ) -> Dict[int, Set[str]]:
        """fn-node-id -> set of blocking descriptions reachable from it."""
        blocking: Dict[int, Set[str]] = {}
        for fn in package.functions:
            direct = {
                name for _node, name in self._direct_blocking(fn)
            }
            if direct:
                blocking[id(fn.node)] = direct
        changed = True
        while changed:
            changed = False
            for fn in package.functions:
                for node in _stmt_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = package.resolve_call(fn, node)
                    if callee is None:
                        continue
                    sub = blocking.get(id(callee.node))
                    if not sub:
                        continue
                    cur = blocking.setdefault(id(fn.node), set())
                    # propagate the callee NAME only (bounded strings)
                    tag = f"{call_name(node)}()"
                    if tag not in cur:
                        cur.add(tag)
                        changed = True
        return blocking

    # -- transitive acquisition closure ---------------------------------------

    # Generic method names whose unresolved calls UNION into the lock
    # closure anyway.  Curated by the dynamic witness: each entry is a
    # name the cross-check caught acquiring a lock the static graph
    # didn't know about (store.add under the pipeline suppress lock,
    # gauge.set from the breaker board, histogram/digest observe under
    # everything).  Do NOT widen casually — a name like ``get`` or
    # ``close`` unions wildly unrelated classes and manufactures phantom
    # cycles; grow this set exactly when the witness gate reports a new
    # missing edge through a generic name.
    UNION_FALLBACK_ATTRS = frozenset({"add", "set", "observe"})

    def _lock_callees(
        self, package: Package, fn: FunctionInfo, node: ast.Call
    ) -> List[FunctionInfo]:
        """Callees for LOCK-CLOSURE purposes.  Exact resolution first;
        when it abstains: a class construction reaches its ``__init__``,
        and a call to one of the witness-curated generic names unions
        every same-named package METHOD.  For an acquisition CLOSURE,
        over-approximating which locks a call may take is the
        conservative direction — it can only add edges the cycle scan
        must then prove consistent."""
        exact = package.resolve_call(fn, node)
        if exact is not None:
            return [exact]
        name = call_name(node)
        if not name:
            return []
        attr = name.rsplit(".", 1)[-1]
        # ClassName(...) -> ClassName.__init__
        if "." not in name and name[:1].isupper():
            cands = [
                f
                for f in package.by_bare_name.get("__init__", ())
                if f.class_name == name
            ]
            if len(cands) == 1:
                return cands
        # receiver-name hint: `self.registry.get(...)` resolves to a
        # method of a class whose NAME matches the receiver (Document-
        # Registry), even for generic attrs.  The witness caught
        # `wait_indexed` holding _done_cv into DocumentRegistry.get this
        # way.  ≥4 chars so `d.get`/`r.state` can't match everything.
        if "." in name:
            recv_tail = name.rsplit(".", 2)[-2].lstrip("_").lower()
            if len(recv_tail) >= 4:
                hinted = [
                    f
                    for f in package.by_bare_name.get(attr, ())
                    if f.class_name is not None
                    and recv_tail in f.class_name.lower()
                ]
                if 0 < len(hinted) <= 4:
                    return hinted
        if attr in self.UNION_FALLBACK_ATTRS:
            # bare names included: `registry.gauge(...).set(...)` chains
            # collapse to a bare `set` (the receiver is a Call), and the
            # witness caught exactly that edge.  Phantom matches (a
            # builtin `set()` constructor) only add edges INTO leaf
            # metric locks, which have no out-edges to cycle through.
            head = name.split(".")[0]
            origin = fn.module.imports.get(head) if "." in name else None
            if origin is not None and origin.split(".")[0] != (
                fn.module.name.split(".")[0]
            ):
                return []  # external-module receiver never enters the pkg
            methods = [
                f
                for f in package.by_bare_name.get(attr, ())
                if f.class_name is not None
            ]
            if 0 < len(methods) <= 6:
                return methods
        return []

    def _locks_closure(
        self, package: Package, known_locks: Set[str]
    ) -> Dict[int, Set[str]]:
        """fn-node-id -> every lock id the function may acquire, through
        the TRANSITIVE closure of package calls (``_lock_callees``).  The
        direct version missed e.g. ``_pop_free_slots -> _finish -> with
        req.cv`` (two frames down) — exactly the edges the dynamic
        witness sees at runtime, so without the closure every witnessed
        deep edge would fail the witness-vs-static cross-check."""
        closure: Dict[int, Set[str]] = {}
        for fn in package.functions:
            direct = self._direct_locks(fn, known_locks)
            if direct:
                closure[id(fn.node)] = set(direct)
        changed = True
        while changed:
            changed = False
            for fn in package.functions:
                for node in _stmt_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee in self._lock_callees(package, fn, node):
                        sub = closure.get(id(callee.node))
                        if not sub:
                            continue
                        cur = closure.setdefault(id(fn.node), set())
                        if not sub <= cur:
                            cur |= sub
                            changed = True
        return closure

    # -- main -----------------------------------------------------------------

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        edges = self.build_graph(package, out)
        # full DFS cycle detection over the canonicalized graph (the
        # 2-cycle-only scan this replaces is the PR-8 satellite fix,
        # validated against the dynamic witness's own cycle scan)
        for cycle in find_cycles(edges.keys()):
            path, line, sym = edges[(cycle[0], cycle[1])]
            pretty = " -> ".join(cycle)
            others = "; ".join(
                f"{a} -> {b} in {edges[(a, b)][2]} "
                f"({edges[(a, b)][0]}:{edges[(a, b)][1]})"
                for a, b in zip(cycle[1:], cycle[2:])
            )
            out.append(
                Finding(
                    self.rule,
                    path,
                    line,
                    sym,
                    f"inconsistent lock order: cycle {pretty} "
                    f"({cycle[0]} -> {cycle[1]} here; {others})",
                )
            )
        return out

    def build_graph(
        self, package: Package, out: Optional[List[Finding]] = None
    ) -> Dict[Tuple[str, str], Tuple[str, int, str]]:
        """The static acquisition-order graph: (A, B) -> first example
        site where B was acquired (directly or through calls) while A
        was held.  Edge endpoints are canonicalized through the
        Condition→lock alias map.  ``analysis/race_witness.py`` holds its
        witnessed edges to membership in THIS graph."""
        decls = discover_locks(package)
        aliases = lock_aliases(decls)
        known_locks = self._discover_locks(package) | known_lock_attrs(decls)
        blocking = self._blocking_closure(package)
        closure = self._locks_closure(package, known_locks)
        findings: List[Finding] = out if out is not None else []
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

        for fn in package.functions:
            self._check_fn(
                package, fn, known_locks, blocking, closure, aliases,
                edges, findings,
            )
        return edges

    def _check_fn(
        self,
        package: Package,
        fn: FunctionInfo,
        known_locks: Set[str],
        blocking: Dict[int, Set[str]],
        closure: Dict[int, Set[str]],
        aliases: Dict[str, str],
        edges: Dict,
        out: List[Finding],
    ) -> None:
        module = fn.module

        def add_edge(held_id: str, lock: str, line: int) -> None:
            a = canonical(held_id, aliases)
            b = canonical(lock, aliases)
            if a != b:
                edges.setdefault(
                    (a, b), (module.relpath, line, fn.qualname)
                )

        def visit(node: ast.AST, held: List[Tuple[str, str]]) -> None:
            # held: list of (lock_id, receiver_text)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    acquired: List[Tuple[str, str]] = []
                    for item in child.items:
                        try:
                            text = ast.unparse(item.context_expr)
                        except Exception:
                            text = ""
                        if isinstance(item.context_expr, ast.Call):
                            continue  # with span(...), with open(...) ...
                        if self._is_lock_expr(text, known_locks):
                            lock = self._lock_id(fn, text)
                            # edges from every already-held lock AND from
                            # earlier items of this same with-statement
                            # (`with a, b:` acquires a then b — the
                            # canonical deadlock pair against
                            # `with b: with a:` elsewhere)
                            for h, _r in held + acquired:
                                add_edge(h, lock, child.lineno)
                            acquired.append((lock, text))
                    visit(child, held + acquired)
                    continue
                if isinstance(child, ast.Call) and held:
                    name = call_name(child)
                    attr = name.rsplit(".", 1)[-1] if name else ""
                    receiver = name.rsplit(".", 1)[0] if "." in name else ""
                    held_receivers = {r for _h, r in held}
                    if attr in ("wait", "notify", "notify_all") and (
                        receiver in held_receivers
                    ):
                        pass  # cv ops on the held lock are the pattern
                    elif _is_blocking_call(module, child) is not None:
                        out.append(
                            Finding(
                                self.rule,
                                module.relpath,
                                child.lineno,
                                fn.qualname,
                                f"blocking call {name}() while holding "
                                f"{held[-1][0]}",
                            )
                        )
                    else:
                        callee = package.resolve_call(fn, child)
                        if callee is not None:
                            sub = blocking.get(id(callee.node))
                            if sub:
                                out.append(
                                    Finding(
                                        self.rule,
                                        module.relpath,
                                        child.lineno,
                                        fn.qualname,
                                        f"call {name}() blocks (via "
                                        f"{sorted(sub)[0]}) while holding "
                                        f"{held[-1][0]}",
                                    )
                                )
                        # cross-call lock-order edges, through the
                        # TRANSITIVE acquisition closures of everything
                        # the call may reach (over-approximating callees
                        # — see _lock_callees)
                        for cand in self._lock_callees(
                            package, fn, child
                        ):
                            for lock in closure.get(id(cand.node), ()):
                                for h, _r in held:
                                    add_edge(h, lock, child.lineno)
                visit(child, held)

        visit(fn.node, [])

    def _direct_locks(
        self, fn: FunctionInfo, known_locks: Set[str]
    ) -> Set[str]:
        out: Set[str] = set()
        for node in _stmt_walk(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        continue
                    try:
                        text = ast.unparse(item.context_expr)
                    except Exception:
                        continue
                    if self._is_lock_expr(text, known_locks):
                        out.add(self._lock_id(fn, text))
        return out


def build_acquisition_graph(package: Package):
    """Module-level convenience for the dynamic witness and tests: the
    canonicalized static acquisition-order graph, without findings."""
    return LockDisciplineChecker().build_graph(package)
