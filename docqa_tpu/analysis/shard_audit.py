"""docqa-shardcheck Tier B: lower the device-plane programs, count their
collectives, and hold the counts to a checked-in budget.

Tier A (mesh-axes / spec-shape / donation, ``analysis/*.py``) proves the
*annotations* are coherent; this module proves what GSPMD actually
*derives* from them.  Each audited program is lowered AOT — abstract
``ShapeDtypeStruct`` inputs, no weights materialized — on three virtual
CPU meshes (1x1, 2x4, 1x8; ``--xla_force_host_platform_device_count=8``),
the partitioned module text is parsed, and every collective op is counted
against ``shard_budget.json``.  The contracts that previously lived only
in comments become red builds:

* **decoder (Megatron TP)** — exactly ONE all-reduce per Megatron block
  (the row-parallel ``wo`` and ``w_down`` projections: two blocks per
  layer), zero all-gathers: the column/row split keeps every other edge
  local.  A spec edit that replicates a weight or reshards an activation
  shows up as an extra all-gather/all-reduce here, not as a mystery 8x
  step-time regression on the pod.
* **ring attention** — exactly n-1 ``ppermute`` rotation rounds on an
  n-device ring (measured from the lowered loop trip count), two
  ppermutes (K and V) per round, nothing else.
* **fused retrieve** — exactly the two tiny all-gathers of the top-k
  merge (values + ids), zero all-reduces/all-gathers anywhere else on
  the path: the corpus scan itself never leaves the shard.

The budget also carries a **jit-root ledger**: every traced root the
package declares (enumerated by jit-purity's discovery pass, so the two
tiers can't disagree about what "traced" means) must be either covered by
an audit program or explicitly waived with a reason.  A new ``jax.jit``
site therefore fails the gate until its collective story is stated.

Entry points: ``scripts/shard_audit.py`` (CLI; CI uploads its ``--report``
JSON as the collective-count trend artifact) and ``pytest -m lint``
(tests/test_shard_audit.py).  See docs/SHARDING.md for the budget file
format and how to amend it deliberately.
"""

from __future__ import annotations

import functools
import json
import os
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# HLO instruction names counted from the partitioned module (sync and
# async-start forms; ``-done`` completes a counted start and is skipped).
HLO_COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# mesh name -> (data, model); the three shapes every program must lower on
MESH_SHAPES: Dict[str, Tuple[int, int]] = {
    "1x1": (1, 1),
    "2x4": (2, 4),
    "1x8": (1, 8),
}

AUDIT_PROGRAMS = (
    "decoder_decode",
    "decoder_prefill",
    "decoder_paged_decode",
    "decoder_ragged_prefill",
    "ring_attention",
    "ulysses_attention",
    "retrieve_fused",
    "retrieve_ivf_sharded",
    "retrieve_lexical_sharded",
    "retrieve_hybrid_sharded",
)


def default_budget_path() -> str:
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), "shard_budget.json")


# ---------------------------------------------------------------------------
# counting
# ---------------------------------------------------------------------------


def count_hlo_collectives(hlo_text: str) -> Dict[str, int]:
    """Collective instruction counts from (partitioned, optimized) HLO
    module text — ``%x = bf16[...] all-reduce(...)`` and the async
    ``all-reduce-start`` form; ``-done`` ops are completions, not new
    collectives."""
    out: Dict[str, int] = {}
    for op in HLO_COLLECTIVES:
        # result type may be a spacey tuple — `= (f32[..], f32[..])
        # all-to-all(` — so match anything between `=` and the opcode;
        # metadata op_names use the jax (underscore) spellings and cannot
        # collide with the hyphenated HLO opcodes
        out[op] = len(
            re.findall(rf"= .*? {re.escape(op)}(?:-start)?\(", hlo_text)
        )
    return out


def _walk_jaxprs(jaxpr) -> "list":
    """Depth-first eqn list over nested jaxprs (duck-typed: anything with
    ``.eqns`` or a ``.jaxpr`` attribute recurses)."""
    pairs = []
    stack = [jaxpr]
    seen = set()
    while stack:
        jx = stack.pop()
        jx = getattr(jx, "jaxpr", jx)  # ClosedJaxpr -> Jaxpr
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        for eqn in jx.eqns:
            pairs.append(eqn)
            for value in eqn.params.values():
                for sub in (
                    value if isinstance(value, (list, tuple)) else [value]
                ):
                    if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                        stack.append(sub)
    return pairs


def jaxpr_ring_rounds(closed_jaxpr) -> List[int]:
    """Trip counts of every lowered loop whose body rotates KV shards
    (contains a ppermute) — the ring rounds the device actually runs, as
    opposed to the static op count in the module text."""
    rounds: List[int] = []
    for eqn in _walk_jaxprs(closed_jaxpr):
        if eqn.primitive.name not in ("scan", "while"):
            continue
        body = eqn.params.get("jaxpr") or eqn.params.get("body_jaxpr")
        if body is None:
            continue
        inner = [e.primitive.name for e in _walk_jaxprs(body)]
        if "ppermute" in inner:
            length = eqn.params.get("length")
            if length is not None:
                rounds.append(int(length))
    return rounds


# ---------------------------------------------------------------------------
# audit configs (small enough to lower in seconds, shardable on 1x8)
# ---------------------------------------------------------------------------


def _audit_decoder_cfg():
    from docqa_tpu.config import DecoderConfig

    # every sharded dim divisible by 8 (the largest model-axis size)
    return DecoderConfig(
        vocab_size=128,
        hidden_dim=64,
        num_layers=2,
        num_heads=8,
        num_kv_heads=8,
        head_dim=8,
        mlp_dim=128,
        max_seq_len=64,
    )


def _audit_encoder_cfg():
    from docqa_tpu.config import EncoderConfig

    return EncoderConfig(
        vocab_size=128,
        hidden_dim=32,
        num_layers=1,
        num_heads=4,
        mlp_dim=64,
        max_seq_len=16,
        embed_dim=32,
        dtype="float32",
    )


def _mesh(name: str):
    from docqa_tpu.runtime.mesh import host_cpu_mesh

    data, model = MESH_SHAPES[name]
    return host_cpu_mesh(data * model, data=data)


def _decoder_abstract_args(cfg, batch: int, seq: int, cache_len: int):
    import jax
    import jax.numpy as jnp

    from docqa_tpu.models.decoder import decoder_param_schema

    params = {
        name: jax.ShapeDtypeStruct(
            shape, jnp.float32 if kind == "ones" else jnp.bfloat16
        )
        for name, kind, shape, _fan in decoder_param_schema(cfg)
    }
    cache = {
        f"{kv}{i}": jax.ShapeDtypeStruct(
            (batch, cache_len, cfg.num_kv_heads, cfg.head_dim), jnp.bfloat16
        )
        for i in range(cfg.num_layers)
        for kv in ("k", "v")
    }
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    lengths = jax.ShapeDtypeStruct((batch,), jnp.int32)
    return params, cache, ids, lengths


def _audit_decoder(mesh_name: str, prefill: bool, pspec_fn=None):
    """Lower one decoder step under the Megatron layout; returns
    (collective counts, meta).  ``pspec_fn`` overrides
    ``decoder_param_pspecs`` so the mutation tests can audit a broken
    layout without editing the real one."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.models.decoder import decoder_forward
    from docqa_tpu.parallel.sharding import cache_pspecs, decoder_param_pspecs

    cfg = _audit_decoder_cfg()
    mesh = _mesh(mesh_name)
    batch, cache_len = 4, 32
    seq = 8 if prefill else 1
    params, cache, ids, lengths = _decoder_abstract_args(
        cfg, batch, seq, cache_len
    )
    pspecs = (pspec_fn or decoder_param_pspecs)(cfg, mesh.model_axis)
    cspecs = cache_pspecs(cfg, mesh)

    if prefill:

        def program(params, cache, ids, lengths):
            return decoder_forward(
                params, cfg, ids, cache,
                jax.numpy.zeros_like(lengths), attn_lengths=lengths,
                last_token_only=True,
            )

    else:

        def program(params, cache, ids, lengths):
            return decoder_forward(params, cfg, ids, cache, lengths)

    in_shardings = (
        {k: NamedSharding(mesh.mesh, pspecs[k]) for k in params},
        {k: NamedSharding(mesh.mesh, cspecs[k]) for k in cache},
        NamedSharding(mesh.mesh, P(mesh.data_axis, None)),
        NamedSharding(mesh.mesh, P(mesh.data_axis)),
    )
    compiled = (
        jax.jit(program, in_shardings=in_shardings)
        .lower(params, cache, ids, lengths)
        .compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    meta = {
        "num_layers": cfg.num_layers,
        # Megatron blocks: the row-parallel projections (attention wo,
        # MLP w_down) — each owes exactly one all-reduce on a TP mesh
        "megatron_blocks": 2 * cfg.num_layers,
        "model_parallel": mesh.n_model,
    }
    return counts, meta


def _audit_paged(mesh_name: str, prefill: bool):
    """Lower the PAGED serving programs (engines/paged.py) under the
    same Megatron layout: the block-pool gather/scatter must not change
    the collective story — still exactly one all-reduce per Megatron
    block, zero all-gathers (the pool shards kv-heads over ``model``,
    its flat block-row axis is replicated, and every table index rides
    that unsharded axis).  This is the ISSUE's "unchanged collective
    budget" evidence for the paged KV tentpole."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.engines.paged import (
        paged_decode_forward,
        ragged_prefill_forward,
    )
    from docqa_tpu.parallel.sharding import (
        decoder_param_pspecs,
        paged_pool_pspecs,
    )

    cfg = _audit_decoder_cfg()
    mesh = _mesh(mesh_name)
    slots, block_size, n_blocks = 4, 8, 16
    rope_len = 32
    params, _cache, _ids, _lengths = _decoder_abstract_args(cfg, slots, 1, 8)
    pools = {
        f"{kv}{i}": jax.ShapeDtypeStruct(
            (n_blocks * block_size, cfg.num_kv_heads, cfg.head_dim),
            jnp.bfloat16,
        )
        for i in range(cfg.num_layers)
        for kv in ("k", "v")
    }
    pspecs = decoder_param_pspecs(cfg, mesh.model_axis)
    pool_specs = paged_pool_pspecs(cfg, mesh)
    replicated = NamedSharding(mesh.mesh, P())
    param_shardings = {
        k: NamedSharding(mesh.mesh, pspecs[k]) for k in params
    }
    pool_shardings = {
        k: NamedSharding(mesh.mesh, pool_specs[k]) for k in pools
    }

    if prefill:
        T = 128

        def program(params, pools, ids, seg, pos, dest, last_rows):
            return ragged_prefill_forward(
                params, cfg, pools, ids, seg, pos, dest, last_rows,
                rope_len=rope_len,
            )

        args = (
            params,
            pools,
            jax.ShapeDtypeStruct((T,), jnp.int32),
            jax.ShapeDtypeStruct((T,), jnp.int32),
            jax.ShapeDtypeStruct((T,), jnp.int32),
            jax.ShapeDtypeStruct((T,), jnp.int32),
            jax.ShapeDtypeStruct((slots,), jnp.int32),
        )
        in_shardings = (
            param_shardings, pool_shardings,
            replicated, replicated, replicated, replicated, replicated,
        )
    else:

        def program(params, pools, tables, tok, lengths):
            return paged_decode_forward(
                params, cfg, pools, tables, tok, lengths,
                block_size=block_size, rope_len=rope_len,
            )

        args = (
            params,
            pools,
            jax.ShapeDtypeStruct((slots, 4), jnp.int32),
            jax.ShapeDtypeStruct((slots, 1), jnp.int32),
            jax.ShapeDtypeStruct((slots,), jnp.int32),
        )
        in_shardings = (
            param_shardings, pool_shardings,
            replicated, replicated, replicated,
        )
    compiled = (
        jax.jit(program, in_shardings=in_shardings).lower(*args).compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    meta = {
        "num_layers": cfg.num_layers,
        "megatron_blocks": 2 * cfg.num_layers,
        "block_size": block_size,
        "model_parallel": mesh.n_model,
    }
    return counts, meta


def _attention_abstract_args():
    import jax
    import jax.numpy as jnp

    shape = (2, 16, 8, 8)  # [b, s, h, d]; s and h divisible by 8
    x = jax.ShapeDtypeStruct(shape, jnp.float32)
    return x, x, x


def _audit_ring(mesh_name: str):
    import jax

    from docqa_tpu.parallel.ring_attention import ring_attention

    mesh = _mesh(mesh_name)
    q, k, v = _attention_abstract_args()

    def program(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True)

    counts = count_hlo_collectives(
        jax.jit(program).lower(q, k, v).compile().as_text()
    )
    rounds = jaxpr_ring_rounds(jax.make_jaxpr(program)(q, k, v))
    meta = {
        "ring_size": mesh.n_model,
        "ring_rounds": sum(rounds),
        # K and V shards rotate per round; the static module has one loop
        "ppermute_per_round": 2,
    }
    return counts, meta


def _audit_ulysses(mesh_name: str):
    import jax

    from docqa_tpu.parallel.ring_attention import ulysses_attention

    mesh = _mesh(mesh_name)
    q, k, v = _attention_abstract_args()

    def program(q, k, v):
        return ulysses_attention(q, k, v, mesh, causal=True)

    counts = count_hlo_collectives(
        jax.jit(program).lower(q, k, v).compile().as_text()
    )
    return counts, {"group_size": mesh.n_model}


def _audit_retrieve(mesh_name: str):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.engines.retrieve import build_fused_search_program
    from docqa_tpu.models.encoder import init_encoder_params

    cfg = _audit_encoder_cfg()
    mesh = _mesh(mesh_name)
    params = jax.eval_shape(
        functools.partial(init_encoder_params, cfg=cfg),
        jax.random.PRNGKey(0),
    )
    batch, capacity = 4, 64
    ids = jax.ShapeDtypeStruct((batch, cfg.max_seq_len), jnp.int32)
    lengths = jax.ShapeDtypeStruct((batch,), jnp.int32)
    buf = jax.ShapeDtypeStruct((capacity, cfg.embed_dim), jnp.float32)
    count = jax.ShapeDtypeStruct((), jnp.int32)

    sharded = mesh.n_model > 1
    program = build_fused_search_program(
        cfg, mesh if sharded else None, k=4, masked=False
    )
    replicated = NamedSharding(mesh.mesh, P())
    in_shardings = (
        jax.tree_util.tree_map(lambda _: replicated, params),
        replicated,
        replicated,
        NamedSharding(
            mesh.mesh, P(mesh.model_axis, None) if sharded else P()
        ),
        replicated,
    )
    compiled = (
        jax.jit(program, in_shardings=in_shardings)
        .lower(params, ids, lengths, buf, count)
        .compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    return counts, {"row_shards": mesh.n_model if sharded else 1}


def _audit_retrieve_ivf(mesh_name: str):
    """Lower the mesh-native fused TIERED retrieve program
    (``engines/retrieve.py:build_tiered_search_program`` — encoder
    forward -> coarse probe over mesh-sharded int8 cell tiles -> exact
    tail scan): the cell tiles/scales/ids shard rows over ``model``,
    the coarse centroid score replicates, each shard scores its local
    tiles, and the only collective content is the 2-gather top-k merge
    (vals + ids) — the same budget the exact store's ``sharded_topk``
    pays.  1x1 lowers the single-device kernel and must be
    collective-free (docqa-meshindex; ROADMAP item 2)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.engines.retrieve import build_tiered_search_program
    from docqa_tpu.index.ivf import ivf_cell_specs
    from docqa_tpu.models.encoder import init_encoder_params

    cfg = _audit_encoder_cfg()
    mesh = _mesh(mesh_name)
    params = jax.eval_shape(
        functools.partial(init_encoder_params, cfg=cfg),
        jax.random.PRNGKey(0),
    )
    batch = 4
    n_cells, cap, n_spill, tail_rows = 16, 8, 4, 32  # cells divisible by 8
    ids = jax.ShapeDtypeStruct((batch, cfg.max_seq_len), jnp.int32)
    lengths = jax.ShapeDtypeStruct((batch,), jnp.int32)
    cells = jax.ShapeDtypeStruct((n_cells, cap, cfg.embed_dim), jnp.int8)
    scale = jax.ShapeDtypeStruct((n_cells, cap), jnp.float32)
    cell_ids = jax.ShapeDtypeStruct((n_cells, cap), jnp.int32)
    centroids = jax.ShapeDtypeStruct((n_cells, cfg.embed_dim), jnp.float32)
    spill = jax.ShapeDtypeStruct((n_spill, cfg.embed_dim), jnp.float32)
    spill_ids = jax.ShapeDtypeStruct((n_spill,), jnp.int32)
    tail = jax.ShapeDtypeStruct((tail_rows, cfg.embed_dim), jnp.float32)
    n_live = jax.ShapeDtypeStruct((), jnp.int32)

    sharded = mesh.n_model > 1
    program = build_tiered_search_program(
        cfg, mesh if sharded else None,
        nprobe=4, fetch=8, k_tail=4, n_real_cells=n_cells,
    )
    replicated = NamedSharding(mesh.mesh, P())
    cell_specs = ivf_cell_specs(mesh.model_axis)
    in_shardings = (
        jax.tree_util.tree_map(lambda _: replicated, params),
        replicated,  # ids
        replicated,  # lengths
        NamedSharding(mesh.mesh, cell_specs[0] if sharded else P()),
        NamedSharding(mesh.mesh, cell_specs[1] if sharded else P()),
        NamedSharding(mesh.mesh, cell_specs[2] if sharded else P()),
        replicated,  # centroids
        replicated,  # spill
        replicated,  # spill_ids
        replicated,  # tail
        replicated,  # n_live
    )
    compiled = (
        jax.jit(program, in_shardings=in_shardings)
        .lower(
            params, ids, lengths, cells, scale, cell_ids, centroids,
            spill, spill_ids, tail, n_live,
        )
        .compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    return counts, {
        "row_shards": mesh.n_model if sharded else 1,
        "storage": "int8",
    }


def _lexical_operand_structs(rows: int = 64, width: int = 8, batch: int = 4,
                             q_terms: int = 16):
    """Abstract operands for the lexical impact-tile kernel (rows
    divisible by 8 so every audit mesh shards them evenly)."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((rows, width), jnp.int32),  # term_ids
        jax.ShapeDtypeStruct((rows, width), jnp.int8),  # impacts
        jax.ShapeDtypeStruct((rows,), jnp.bool_),  # row_live
        jax.ShapeDtypeStruct((batch, q_terms), jnp.int32),  # q_terms
        jax.ShapeDtypeStruct((batch, q_terms), jnp.float32),  # q_weights
    )


def _audit_retrieve_lexical(mesh_name: str):
    """Lower the lexical tier's search program
    (``index/lexical.py:build_lexical_search_program`` — impact-tile
    scoring over row-sharded int8 tiles -> top-k): tiles/liveness shard
    rows over ``model``, queries replicate, each shard scores its local
    rows in f32 (preferred_element_type) and the only collective content
    is the SAME 2-gather top-k merge (vals + ids) the dense tiers pay.
    1x1 lowers the single-device kernel and must be collective-free
    (docqa-lexroute)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.index.lexical import (
        build_lexical_search_program,
        lexical_specs,
    )

    mesh = _mesh(mesh_name)
    sharded = mesh.n_model > 1
    operands = _lexical_operand_structs()
    program = build_lexical_search_program(mesh if sharded else None, k=4)
    specs = lexical_specs(mesh.model_axis)
    in_shardings = tuple(
        NamedSharding(mesh.mesh, spec if sharded else P())
        for spec in specs
    )
    compiled = (
        jax.jit(program, in_shardings=in_shardings)
        .lower(*operands)
        .compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    return counts, {
        "row_shards": mesh.n_model if sharded else 1,
        "storage": "lexical_int8",
    }


def _audit_retrieve_hybrid(mesh_name: str):
    """Lower the single-dispatch HYBRID retrieve program
    (``engines/retrieve.py:build_hybrid_search_program`` — the audited
    tiered dense program PLUS the audited lexical kernel in one XLA
    program).  On a mesh both tier scans enter their ``shard_map`` merge
    kernels inside the same dispatch, so the program owes exactly TWO
    2-gather merge pairs (dense probe + lexical) and nothing else; 1x1
    must stay collective-free (docqa-lexroute)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from docqa_tpu.engines.retrieve import build_hybrid_search_program
    from docqa_tpu.index.ivf import ivf_cell_specs
    from docqa_tpu.index.lexical import lexical_specs
    from docqa_tpu.models.encoder import init_encoder_params

    cfg = _audit_encoder_cfg()
    mesh = _mesh(mesh_name)
    params = jax.eval_shape(
        functools.partial(init_encoder_params, cfg=cfg),
        jax.random.PRNGKey(0),
    )
    batch = 4
    n_cells, cap, n_spill, tail_rows = 16, 8, 4, 32  # cells divisible by 8
    ids = jax.ShapeDtypeStruct((batch, cfg.max_seq_len), jnp.int32)
    lengths = jax.ShapeDtypeStruct((batch,), jnp.int32)
    cells = jax.ShapeDtypeStruct((n_cells, cap, cfg.embed_dim), jnp.int8)
    scale = jax.ShapeDtypeStruct((n_cells, cap), jnp.float32)
    cell_ids = jax.ShapeDtypeStruct((n_cells, cap), jnp.int32)
    centroids = jax.ShapeDtypeStruct((n_cells, cfg.embed_dim), jnp.float32)
    spill = jax.ShapeDtypeStruct((n_spill, cfg.embed_dim), jnp.float32)
    spill_ids = jax.ShapeDtypeStruct((n_spill,), jnp.int32)
    tail = jax.ShapeDtypeStruct((tail_rows, cfg.embed_dim), jnp.float32)
    n_live = jax.ShapeDtypeStruct((), jnp.int32)
    lex_operands = _lexical_operand_structs(batch=batch)

    sharded = mesh.n_model > 1
    program = build_hybrid_search_program(
        cfg, mesh if sharded else None,
        nprobe=4, fetch=8, k_tail=4, k_lex=4, n_real_cells=n_cells,
    )
    replicated = NamedSharding(mesh.mesh, P())
    cell_specs = ivf_cell_specs(mesh.model_axis)
    lex_specs = lexical_specs(mesh.model_axis)
    in_shardings = (
        jax.tree_util.tree_map(lambda _: replicated, params),
        replicated,  # ids
        replicated,  # lengths
        NamedSharding(mesh.mesh, cell_specs[0] if sharded else P()),
        NamedSharding(mesh.mesh, cell_specs[1] if sharded else P()),
        NamedSharding(mesh.mesh, cell_specs[2] if sharded else P()),
        replicated,  # centroids
        replicated,  # spill
        replicated,  # spill_ids
        replicated,  # tail
        replicated,  # n_live
    ) + tuple(
        NamedSharding(mesh.mesh, spec if sharded else P())
        for spec in lex_specs
    )
    compiled = (
        jax.jit(program, in_shardings=in_shardings)
        .lower(
            params, ids, lengths, cells, scale, cell_ids, centroids,
            spill, spill_ids, tail, n_live, *lex_operands,
        )
        .compile()
    )
    counts = count_hlo_collectives(compiled.as_text())
    return counts, {
        "row_shards": mesh.n_model if sharded else 1,
        "storage": "int8+lexical_int8",
    }


_AUDITS: Dict[str, Callable[[str], Tuple[Dict[str, int], Dict[str, Any]]]] = {
    "decoder_decode": functools.partial(_audit_decoder, prefill=False),
    "decoder_prefill": functools.partial(_audit_decoder, prefill=True),
    "decoder_paged_decode": functools.partial(_audit_paged, prefill=False),
    "decoder_ragged_prefill": functools.partial(_audit_paged, prefill=True),
    "ring_attention": _audit_ring,
    "ulysses_attention": _audit_ulysses,
    "retrieve_fused": _audit_retrieve,
    "retrieve_ivf_sharded": _audit_retrieve_ivf,
    "retrieve_lexical_sharded": _audit_retrieve_lexical,
    "retrieve_hybrid_sharded": _audit_retrieve_hybrid,
}


# ---------------------------------------------------------------------------
# jit-root ledger
# ---------------------------------------------------------------------------


def enumerate_jit_roots(package=None) -> List[str]:
    """Stable symbols for every traced root jit-purity discovers:
    ``<relpath>:<qualname>`` for defs, ``...<qualname>.<lambda>`` (with
    ``#n`` suffixes for siblings) for lambdas."""
    from docqa_tpu.analysis.core import Package
    from docqa_tpu.analysis.jit_purity import discover_jit_roots

    if package is None:
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        package = Package.load(pkg_dir)
    traced, lambdas = discover_jit_roots(package)
    # the audit's own lowering closures are harness, not serving code
    symbols = [
        f"{fn.module.relpath}:{fn.qualname}"
        for fn, _via in traced.values()
        if not fn.module.relpath.startswith("analysis/")
    ]
    seen: Dict[str, int] = {}
    for fn, _lam, _via in lambdas:
        if fn.module.relpath.startswith("analysis/"):
            continue
        base = f"{fn.module.relpath}:{fn.qualname}.<lambda>"
        n = seen.get(base, 0) + 1
        seen[base] = n
        symbols.append(base if n == 1 else f"{base}#{n}")
    return sorted(symbols)


# ---------------------------------------------------------------------------
# run + compare
# ---------------------------------------------------------------------------


def run_audit(
    mesh_names: Optional[Sequence[str]] = None,
    programs: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Lower every audited program on every mesh; returns the report
    (the CI artifact): measured collective counts + meta + the discovered
    jit-root symbols."""
    mesh_names = list(mesh_names or MESH_SHAPES)
    programs = list(programs or AUDIT_PROGRAMS)
    report: Dict[str, Any] = {"programs": {}, "jit_roots": {}}
    for name in programs:
        per_mesh: Dict[str, Any] = {}
        meta: Dict[str, Any] = {}
        for mesh_name in mesh_names:
            counts, m = _AUDITS[name](mesh_name)
            entry = dict(counts)
            # mesh-dependent meta rides with the mesh entry
            for key in ("ring_rounds", "ring_size", "group_size",
                        "row_shards", "model_parallel"):
                if key in m:
                    entry[key] = m.pop(key)
            per_mesh[mesh_name] = entry
            meta.update(m)
        report["programs"][name] = {"meta": meta, "per_mesh": per_mesh}
    report["jit_roots"] = {"discovered": enumerate_jit_roots()}
    return report


def _model_dim(mesh_name: str) -> int:
    return MESH_SHAPES[mesh_name][1]


def semantic_violations(report: Dict[str, Any]) -> List[str]:
    """Invariants checked against the MEASUREMENT (not the budget), so an
    'update the budget to whatever it prints' workflow still cannot admit
    a layout that breaks the stated contracts."""
    out: List[str] = []
    progs = report.get("programs", {})

    for name in (
        "decoder_decode",
        "decoder_prefill",
        "decoder_paged_decode",
        "decoder_ragged_prefill",
    ):
        prog = progs.get(name)
        if not prog:
            continue
        blocks = prog["meta"].get("megatron_blocks", 0)
        for mesh_name, counts in prog["per_mesh"].items():
            tp = _model_dim(mesh_name) > 1
            want_ar = blocks if tp else 0
            if counts.get("all-reduce") != want_ar:
                out.append(
                    f"{name}/{mesh_name}: {counts.get('all-reduce')} "
                    f"all-reduce(s) for {blocks} Megatron block(s) — the "
                    f"layout owes exactly one per block on a TP mesh "
                    f"(expected {want_ar})"
                )
            for op in ("all-gather", "all-to-all", "collective-permute"):
                if counts.get(op, 0):
                    out.append(
                        f"{name}/{mesh_name}: unexpected {op} x"
                        f"{counts[op]} — the Megatron layout keeps every "
                        f"non-psum edge local"
                    )

    prog = progs.get("ring_attention")
    if prog:
        for mesh_name, counts in prog["per_mesh"].items():
            n = counts.get("ring_size", _model_dim(mesh_name))
            want = n - 1 if n > 1 else 0
            if counts.get("ring_rounds") != want:
                out.append(
                    f"ring_attention/{mesh_name}: {counts.get('ring_rounds')}"
                    f" ppermute round(s) on a {n}-device ring — a ring "
                    f"needs exactly n-1 (= {want}); the n-th rotation is "
                    f"pure wasted ICI"
                )
            for op in ("all-gather", "all-reduce", "all-to-all"):
                if counts.get(op, 0):
                    out.append(
                        f"ring_attention/{mesh_name}: unexpected {op} x"
                        f"{counts[op]} — the ring only rotates KV shards"
                    )

    prog = progs.get("ulysses_attention")
    if prog:
        for mesh_name, counts in prog["per_mesh"].items():
            grouped = _model_dim(mesh_name) > 1
            want = 4 if grouped else 0  # q/k/v reshuffle in + output back
            if counts.get("all-to-all") != want:
                out.append(
                    f"ulysses_attention/{mesh_name}: "
                    f"{counts.get('all-to-all')} all-to-all(s) — the "
                    f"seq<->head reshuffle owes exactly {want}"
                )
            for op in ("all-gather", "all-reduce", "collective-permute"):
                if counts.get(op, 0):
                    out.append(
                        f"ulysses_attention/{mesh_name}: unexpected {op} x"
                        f"{counts[op]}"
                    )

    # every retrieve program owes the SAME collective story: each tier
    # scan pays exactly one (vals, ids) all-gather pair for its top-k
    # merge, nothing else — the corpus scan itself never leaves the
    # shard, and 1x1 lowers the single-device kernel collective-free.
    # The hybrid program runs TWO tier scans (dense probe + lexical) in
    # one dispatch, so it owes two merge pairs (docqa-lexroute).
    for rname, merge_pairs in (
        ("retrieve_fused", 1),
        ("retrieve_ivf_sharded", 1),
        ("retrieve_lexical_sharded", 1),
        ("retrieve_hybrid_sharded", 2),
    ):
        prog = progs.get(rname)
        if not prog:
            continue
        for mesh_name, counts in prog["per_mesh"].items():
            want_ag = 2 * merge_pairs if _model_dim(mesh_name) > 1 else 0
            if counts.get("all-gather") != want_ag:
                out.append(
                    f"{rname}/{mesh_name}: {counts.get('all-gather')} "
                    f"all-gather(s) — the path owes exactly "
                    f"{merge_pairs} top-k merge pair(s) (vals + ids; "
                    f"expected {want_ag})"
                )
            for op in ("all-reduce", "collective-permute", "all-to-all"):
                if counts.get(op, 0):
                    out.append(
                        f"{rname}/{mesh_name}: unexpected {op} x"
                        f"{counts[op]} on the retrieve path"
                    )
    return out


def compare_budget(
    report: Dict[str, Any], budget: Dict[str, Any]
) -> List[str]:
    """Violations of the checked-in budget: any measured-vs-granted count
    drift, any program/mesh missing on either side, any jit root neither
    covered nor waived (or waived without a real reason), plus the
    semantic invariants on the measurement itself."""
    out: List[str] = list(semantic_violations(report))
    want_progs = budget.get("programs", {})
    got_progs = report.get("programs", {})
    for name in sorted(set(want_progs) | set(got_progs)):
        if name not in got_progs:
            out.append(f"budget program '{name}' was not audited (stale?)")
            continue
        if name not in want_progs:
            out.append(f"program '{name}' has no budget entry")
            continue
        want_meshes = want_progs[name].get("per_mesh", {})
        got_meshes = got_progs[name].get("per_mesh", {})
        for mesh_name in sorted(set(want_meshes) | set(got_meshes)):
            want = want_meshes.get(mesh_name)
            got = got_meshes.get(mesh_name)
            if want is None or got is None:
                out.append(
                    f"{name}/{mesh_name}: present in "
                    f"{'report' if want is None else 'budget'} only"
                )
                continue
            for key in sorted(set(want) | set(got)):
                if want.get(key) != got.get(key):
                    out.append(
                        f"{name}/{mesh_name}: {key} = {got.get(key)} "
                        f"(budget grants {want.get(key)})"
                    )

    ledger = budget.get("jit_roots", {})
    discovered = report.get("jit_roots", {}).get("discovered", [])
    for symbol in discovered:
        reason = ledger.get(symbol)
        if reason is None:
            out.append(
                f"new jit root '{symbol}' is neither audited nor waived "
                f"in shard_budget.json"
            )
        elif not str(reason).strip() or "TODO" in str(reason):
            out.append(
                f"jit root '{symbol}' has no real coverage/waiver reason"
            )
    for symbol in sorted(set(ledger) - set(discovered)):
        out.append(
            f"stale jit-root ledger entry '{symbol}' (root no longer "
            f"exists)"
        )
    return out


def load_budget(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or default_budget_path()
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budget(
    report: Dict[str, Any], path: Optional[str] = None
) -> Dict[str, Any]:
    """Regenerate the budget from a report, preserving existing jit-root
    reasons (new roots get a TODO the gate rejects until justified)."""
    path = path or default_budget_path()
    old: Dict[str, Any] = {}
    if os.path.exists(path):
        old = load_budget(path)
    old_ledger = old.get("jit_roots", {})
    budget = {
        "_comment": (
            "Collective budget for the device-plane programs "
            "(docs/SHARDING.md).  Counts are measured from lowered, "
            "partitioned HLO by scripts/shard_audit.py; amend ONLY via "
            "--write-budget plus a reviewed justification of the new "
            "collective.  jit_roots maps every traced root to the audit "
            "program covering it or a waiver reason."
        ),
        "programs": {
            name: {
                "meta": prog.get("meta", {}),
                "per_mesh": prog.get("per_mesh", {}),
            }
            for name, prog in report.get("programs", {}).items()
        },
        "jit_roots": {
            symbol: old_ledger.get(symbol, "TODO: justify")
            for symbol in report.get("jit_roots", {}).get("discovered", [])
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budget, f, indent=2, sort_keys=True)
        f.write("\n")
    return budget
