"""dispatch-streams: device work is SPINE-DELEGATED or it is ledgered.

History: the reproduced CPU-client capacity deadlock (PRs 6–8: >= 3
threads holding concurrent sharded dispatches park the process at 0%
CPU; evidence preserved under ``budget.evidence`` in
``dispatch_streams.json``) was first held off by enumerating every
device-dispatching thread and gating the count against a budget.  The
dispatch spine (``engines/spine.py``) retired the hazard class
architecturally: device work is submitted as work items and executed on
the spine's bounded lanes, so the checker is now RE-POINTED at the
spine boundary:

* **ownership** — a function OWNS a device stream when it can reach a
  jax dispatch on its own thread: a direct ``jax.*``/``jnp.*`` call in
  its own body (nested closures handed to ``spine_run``/``spine_submit``
  are the spine's work, not the caller's; pure wrapper constructors —
  ``jax.jit``, ``ShapeDtypeStruct``, ``eval_shape``, ``shard_map``,
  ``tree_map`` — build programs without dispatching), or a resolvable
  call into an owning function.  Calls INTO the spine module never
  propagate ownership — that is the delegation boundary;
* **the thread gate** — every thread entry point whose target OWNS a
  stream must appear in the ledger with a justification, exactly as
  before.  With full delegation the owning set shrinks to the spine's
  own lane loop (plus conservatively-capable entries whose targets are
  statically unresolvable — executor lanes running caller-supplied
  functions); the entries whose justification was "gated by budget" are
  deleted, and ``budget.max_concurrent_device_streams`` counts stream
  FAMILIES (the spine's internal lane concurrency is its ``n_lanes``
  runtime bound, live on ``/api/telemetry`` as ``dispatch_occupancy``);
* **the stage gate** — every ``spine_run("<stage>", …)`` submission
  site must use a stage name listed under the ledger's ``spine.stages``
  (with a one-line description); unknown stages and stale stage entries
  fail like stale baselines.  Adding a device workload now means naming
  its stage in a reviewed file, not adding the Nth dispatching thread.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from docqa_tpu.analysis.concurrency import (
    ThreadEntry,
    enumerate_thread_entries,
)
from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    GENERIC_NAMES,
    Package,
    call_name,
)

LEDGER_NAME = "dispatch_streams.json"

# the spine submission idiom (engines/spine.py): closures passed to
# these names are executed on spine lanes, never on the calling thread
SPINE_SUBMIT_TAILS = frozenset({"spine_run", "spine_submit"})
_SPINE_MODULE_SUFFIX = os.sep.join(("engines", "spine.py"))

# jax namespace calls that BUILD programs/wrappers without enqueueing
# device work — owning one of these is not owning a stream.
# TraceAnnotation is the profiler scope metrics.span opens (host-only);
# jnp.dtype is a dtype constructor.
_JAX_WRAPPER_TAILS = frozenset(
    {
        "jit", "ShapeDtypeStruct", "eval_shape", "shard_map", "tree_map",
        "TraceAnnotation", "dtype",
    }
)

# method names that mean device work by convention when the call cannot
# be resolved to ANY package function (fixture trees): every `warmup`
# compiles and dispatches
_DISPATCHING_ATTRS = frozenset({"warmup"})


def default_ledger_path() -> str:
    """The checked-in ledger: ``<repo>/dispatch_streams.json``."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), LEDGER_NAME)


def _package_ledger_path(package: Package) -> Optional[str]:
    """Ledger next to the analyzed package's root (fixture trees carry
    their own or none; the real runs resolve to the repo's)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            cand = os.path.join(os.path.dirname(base), LEDGER_NAME)
            if os.path.exists(cand):
                return cand
            cand = os.path.join(base, LEDGER_NAME)
            if os.path.exists(cand):
                return cand
    return None


def load_ledger(path: Optional[str]) -> Dict:
    if not path or not os.path.exists(path):
        return {"streams": {}, "budget": {}, "spine": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("streams", {})
    data.setdefault("budget", {})
    data.setdefault("spine", {})
    return data


def _is_spine_module(fn: FunctionInfo) -> bool:
    rel = fn.module.relpath.replace("/", os.sep)
    return rel.endswith(_SPINE_MODULE_SUFFIX)


def _iter_own_body(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Nodes of a function's OWN body — nested def/lambda subtrees are
    skipped (each nested def is its own FunctionInfo; a closure's device
    work belongs to whoever EXECUTES it, which for spine submissions is
    a ledgered lane, not this function's thread)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _direct_dispatch(fn: FunctionInfo) -> Optional[str]:
    """First jax-namespace call in the function's own body that enqueues
    device work (wrapper constructors excluded), or None."""
    for node in _iter_own_body(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not name:
            continue
        resolved = fn.module.resolve_alias(name)
        if resolved.split(".")[0] == "jax" and "." in resolved:
            if resolved.rsplit(".", 1)[-1] in _JAX_WRAPPER_TAILS:
                continue
            return resolved
    return None


def spine_aware_owners(package: Package) -> Dict[int, str]:
    """fn-node-id -> reason, for functions that OWN device dispatch on
    their calling thread (spine-delegated work excluded).  Fixed point
    over package-resolvable calls; calls into the spine module are the
    delegation boundary and never propagate."""
    cache = getattr(package, "_concurrency_memo", None)
    if cache is None:
        cache = {}
        package._concurrency_memo = cache  # type: ignore[attr-defined]
    if "spine_owners" in cache:
        return cache["spine_owners"]

    inits: Dict[str, FunctionInfo] = {}
    for fn in package.functions:
        if fn.name == "__init__" and fn.class_name:
            inits.setdefault(fn.class_name, fn)

    owners: Dict[int, str] = {}
    for fn in package.functions:
        if _is_spine_module(fn):
            # the spine's own lane machinery is THE ledgered stream
            # family; mark its executor so the lane-loop thread entry is
            # gated, but never let callers inherit it (delegation)
            hit = _direct_dispatch(fn)
            if hit is not None:
                owners[id(fn.node)] = hit
            continue
        hit = _direct_dispatch(fn)
        if hit is not None:
            owners[id(fn.node)] = hit

    def propagated(fn: FunctionInfo) -> Optional[str]:
        for node in _iter_own_body(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            tail = name.rsplit(".", 1)[-1]
            if tail in SPINE_SUBMIT_TAILS:
                continue  # delegated: the spine's lanes execute it
            callee = package.resolve_call(fn, node)
            if callee is not None:
                if _is_spine_module(callee) and not _is_spine_module(fn):
                    continue  # delegation boundary (cross-module only:
                    # the spine's own machinery still chains to its
                    # lane loop, THE ledgered stream family)
                sub = owners.get(id(callee.node))
                if sub is not None:
                    return f"via {callee.qualname} ({sub})"
                continue
            if "." in name:
                if tail in GENERIC_NAMES:
                    continue  # ambiguity never guesses (core.resolve_call)
                # an external-module receiver (np.linalg.norm, os.path.x)
                # never resolves into the package (mirrors resolve_call)
                head = name.rsplit(".", 1)[0].split(".")[0]
                origin = fn.module.imports.get(head)
                pkg_root = fn.module.name.split(".")[0]
                if origin is not None and origin.split(".")[0] != pkg_root:
                    continue
                # candidates are methods/module functions only — a
                # nested def cannot be the target of an attribute call
                cands = [
                    c
                    for c in package.by_bare_name.get(tail, ())
                    if not _is_spine_module(c)
                    and "<locals>" not in c.qualname
                ]
                if cands:
                    for c in cands:
                        sub = owners.get(id(c.node))
                        if sub is not None:
                            return f"via candidate {c.qualname} ({sub})"
                    continue
                if tail in _DISPATCHING_ATTRS:
                    return f"{name} (compile/dispatch by convention)"
            else:
                ctor = inits.get(tail)
                if ctor is not None:
                    sub = owners.get(id(ctor.node))
                    if sub is not None:
                        return f"via {ctor.qualname} ({sub})"
        return None

    changed = True
    while changed:
        changed = False
        for fn in package.functions:
            if id(fn.node) in owners:
                continue
            why = propagated(fn)
            if why is not None:
                owners[id(fn.node)] = why
                changed = True
    cache["spine_owners"] = owners
    return owners


def enumerate_spine_sites(
    package: Package,
) -> List[Tuple[FunctionInfo, int, Optional[str]]]:
    """Every ``spine_run``/``spine_submit`` call site: (enclosing fn,
    lineno, stage literal or None when the stage is dynamic)."""
    out: List[Tuple[FunctionInfo, int, Optional[str]]] = []
    for fn in package.functions:
        for node in _iter_own_body(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name or name.rsplit(".", 1)[-1] not in SPINE_SUBMIT_TAILS:
                continue
            stage: Optional[str] = None
            if node.args and isinstance(node.args[0], ast.Constant) and (
                isinstance(node.args[0].value, str)
            ):
                stage = node.args[0].value
            out.append((fn, node.lineno, stage))
    return out


class DispatchStreamsChecker:
    rule = "dispatch-streams"

    def __init__(self, ledger_path: Optional[str] = None) -> None:
        self.ledger_path = ledger_path

    def check(self, package: Package) -> List[Finding]:
        ledger_path = self.ledger_path or _package_ledger_path(package)
        ledger = load_ledger(ledger_path)
        streams: Dict[str, Dict] = ledger["streams"]
        owners = spine_aware_owners(package)
        out: List[Finding] = []

        present: Dict[str, ThreadEntry] = {}
        for entry in enumerate_thread_entries(package):
            capable, why = self._capability(entry, owners)
            if not capable:
                continue
            present.setdefault(entry.key, entry)
            row = streams.get(entry.key)
            if row is None:
                out.append(
                    Finding(
                        self.rule,
                        entry.module_relpath,
                        entry.lineno,
                        entry.site_qualname,
                        f"unledgered device-dispatch stream {entry.key!r} "
                        f"({why}) — route the device work through the "
                        f"dispatch spine (engines/spine.py spine_run), or "
                        f"add the entry to {LEDGER_NAME} with a "
                        "justification and account for it in the "
                        "concurrency budget",
                    )
                )

        analyzed = {m.relpath for m in package.modules}
        if ledger_path is not None:
            for key, row in sorted(streams.items()):
                rel = key.split(":", 1)[0]
                if rel not in analyzed:
                    continue  # another package's entries (scripts vs pkg)
                if key not in present:
                    out.append(
                        Finding(
                            self.rule,
                            rel,
                            1,
                            "<ledger>",
                            f"stale {LEDGER_NAME} entry {key!r}: no such "
                            "dispatch-owning thread entry point exists "
                            "any more — remove it (and reclaim its "
                            "budget slot)",
                        )
                    )
            budget = ledger["budget"].get("max_concurrent_device_streams")
            if budget is not None and present:
                # PROCESS-WIDE count: entries this package run verified
                # as present, plus every declared entry belonging to
                # another package (docqa_tpu vs scripts/ run over the
                # same ledger — each prunes only its own stale entries,
                # so a scripts-side stream must still count against the
                # one budget here, or splitting the analysis into two
                # Package runs would silently split the budget too)
                concurrent = [
                    key
                    for key, row in sorted(streams.items())
                    if row.get("concurrent_with_serving")
                    and (
                        key in present
                        or key.split(":", 1)[0] not in analyzed
                    )
                ]
                if len(concurrent) > int(budget):
                    anchor = next(
                        (present[k] for k in concurrent if k in present),
                        next(iter(present.values())),
                    )
                    out.append(
                        Finding(
                            self.rule,
                            anchor.module_relpath,
                            anchor.lineno,
                            "<ledger>",
                            f"{len(concurrent)} streams marked "
                            "concurrent_with_serving exceed the ledger "
                            f"budget max_concurrent_device_streams="
                            f"{budget} — device work belongs on the "
                            "dispatch spine (engines/spine.py); raise "
                            "the budget only with new capacity evidence",
                        )
                    )
        out.extend(self._check_spine_stages(package, ledger, ledger_path))
        return out

    def _check_spine_stages(
        self, package: Package, ledger: Dict, ledger_path: Optional[str]
    ) -> List[Finding]:
        """The re-pointed gate: spine submission sites must use stage
        names the ledger's ``spine.stages`` section declares, and every
        declared stage must still have a submission site in SOME
        analyzed package (stale stages are pruned only by the package
        that contains spine sites at all, mirroring the streams rule)."""
        sites = enumerate_spine_sites(package)
        if not sites or ledger_path is None:
            return []
        stages: Dict[str, str] = dict(ledger.get("spine", {}).get(
            "stages", {}
        ))
        out: List[Finding] = []
        used: Set[str] = set()
        for fn, lineno, stage in sites:
            if stage is None:
                continue  # dynamic stage: the submitting API's problem
            used.add(stage)
            if stage not in stages:
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        lineno,
                        fn.qualname,
                        f"spine stage {stage!r} is not declared in "
                        f"{LEDGER_NAME} spine.stages — name the new "
                        "device workload there with a one-line "
                        "description (the reviewed list of everything "
                        "that can occupy a dispatch lane)",
                    )
                )
        for stage in sorted(set(stages) - used):
            out.append(
                Finding(
                    self.rule,
                    package.modules[0].relpath,
                    1,
                    "<ledger>",
                    f"stale spine stage {stage!r} in {LEDGER_NAME}: no "
                    "spine_run/spine_submit site uses it any more — "
                    "remove the entry",
                )
            )
        return out

    @staticmethod
    def _capability(entry: ThreadEntry, owners: Dict[int, str]):
        if entry.target is not None:
            why = owners.get(id(entry.target.node))
            if why is None:
                return False, ""
            return True, f"target {entry.target.qualname} dispatches: {why}"
        return True, (
            f"dynamic target {entry.target_text!r} — unresolvable "
            "statically, conservatively dispatch-capable"
        )
