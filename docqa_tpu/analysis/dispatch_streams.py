"""dispatch-streams: every thread that can reach the device is ledgered.

The still-reproducing CPU-client capacity deadlock (PRs 6–7: batcher
admission + a concurrent sharded retrieve + one more stream — a rebuild
warmup, a canary, the next request's device ops — exceed the virtual-
device client's collective scheduling capacity and the process parks at
0% CPU) is a budget problem: the process grew device-dispatching threads
one PR at a time, and nobody could NAME them all.  This rule enumerates
them statically and holds the set to a checked-in ledger,
``dispatch_streams.json`` — the jit-root-ledger idea applied to threads:

* **entry points** — ``threading.Thread(target=…)``, ``executor
  .submit(…)``, ``loop.run_in_executor(…)`` and ``obs.call_in(…)``
  sites, targets resolved where the package can (``self.method``, bare
  names, ``partial``, lambdas wrapping one resolvable call);
* **dispatch-capable** — the resolved target's transitive package call
  graph reaches a jax dispatch (a ``jax.*``/``jnp.*`` call, a jit root,
  or a class construction that allocates device state); an entry whose
  target CANNOT be resolved (an executor lane running caller-supplied
  functions) is conservatively capable — it must be ledgered with a
  justification saying what it actually runs;
* **the gate** — every dispatch-capable entry point must appear in the
  ledger (with a human justification); stale ledger entries fail like
  stale baselines; and the count of entries marked
  ``concurrent_with_serving`` must stay within the ledger's
  ``max_concurrent_device_streams`` budget — adding a stream means
  bumping a number a reviewer sees, next to the recorded deadlock
  evidence, instead of silently adding the Nth concurrent dispatcher.

The ledger's ``budget.evidence`` carries the recorded stream/lock
witness of the capacity deadlock (``scripts/serve_cluster_loop.py``), so
the precondition is a named, gated number instead of tribal knowledge.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from docqa_tpu.analysis.concurrency import (
    ThreadEntry,
    dispatch_reachable,
    enumerate_thread_entries,
)
from docqa_tpu.analysis.core import Finding, Package

LEDGER_NAME = "dispatch_streams.json"


def default_ledger_path() -> str:
    """The checked-in ledger: ``<repo>/dispatch_streams.json``."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), LEDGER_NAME)


def _package_ledger_path(package: Package) -> Optional[str]:
    """Ledger next to the analyzed package's root (fixture trees carry
    their own or none; the real runs resolve to the repo's)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            cand = os.path.join(os.path.dirname(base), LEDGER_NAME)
            if os.path.exists(cand):
                return cand
            cand = os.path.join(base, LEDGER_NAME)
            if os.path.exists(cand):
                return cand
    return None


def load_ledger(path: Optional[str]) -> Dict:
    if not path or not os.path.exists(path):
        return {"streams": {}, "budget": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("streams", {})
    data.setdefault("budget", {})
    return data


class DispatchStreamsChecker:
    rule = "dispatch-streams"

    def __init__(self, ledger_path: Optional[str] = None) -> None:
        self.ledger_path = ledger_path

    def check(self, package: Package) -> List[Finding]:
        ledger_path = self.ledger_path or _package_ledger_path(package)
        ledger = load_ledger(ledger_path)
        streams: Dict[str, Dict] = ledger["streams"]
        reach = dispatch_reachable(package)
        out: List[Finding] = []

        present: Dict[str, ThreadEntry] = {}
        for entry in enumerate_thread_entries(package):
            capable, why = self._capability(entry, reach)
            if not capable:
                continue
            present.setdefault(entry.key, entry)
            row = streams.get(entry.key)
            if row is None:
                out.append(
                    Finding(
                        self.rule,
                        entry.module_relpath,
                        entry.lineno,
                        entry.site_qualname,
                        f"unledgered device-dispatch stream {entry.key!r} "
                        f"({why}) — add it to {LEDGER_NAME} with a "
                        "justification and account for it in the "
                        "concurrency budget",
                    )
                )

        analyzed = {m.relpath for m in package.modules}
        if ledger_path is not None:
            for key, row in sorted(streams.items()):
                rel = key.split(":", 1)[0]
                if rel not in analyzed:
                    continue  # another package's entries (scripts vs pkg)
                if key not in present:
                    out.append(
                        Finding(
                            self.rule,
                            rel,
                            1,
                            "<ledger>",
                            f"stale {LEDGER_NAME} entry {key!r}: no such "
                            "dispatch-capable thread entry point exists "
                            "any more — remove it (and reclaim its "
                            "budget slot)",
                        )
                    )
            budget = ledger["budget"].get("max_concurrent_device_streams")
            if budget is not None and present:
                # PROCESS-WIDE count: entries this package run verified
                # as present, plus every declared entry belonging to
                # another package (docqa_tpu vs scripts/ run over the
                # same ledger — each prunes only its own stale entries,
                # so a scripts-side stream must still count against the
                # one budget here, or splitting the analysis into two
                # Package runs would silently split the budget too)
                concurrent = [
                    key
                    for key, row in sorted(streams.items())
                    if row.get("concurrent_with_serving")
                    and (
                        key in present
                        or key.split(":", 1)[0] not in analyzed
                    )
                ]
                if len(concurrent) > int(budget):
                    anchor = next(
                        (present[k] for k in concurrent if k in present),
                        next(iter(present.values())),
                    )
                    out.append(
                        Finding(
                            self.rule,
                            anchor.module_relpath,
                            anchor.lineno,
                            "<ledger>",
                            f"{len(concurrent)} streams marked "
                            "concurrent_with_serving exceed the ledger "
                            f"budget max_concurrent_device_streams="
                            f"{budget} — the client-capacity deadlock's "
                            "precondition (see budget.evidence); raise "
                            "the budget only with new capacity evidence",
                        )
                    )
        return out

    @staticmethod
    def _capability(entry: ThreadEntry, reach: Dict[int, str]):
        if entry.target is not None:
            why = reach.get(id(entry.target.node))
            if why is None:
                return False, ""
            return True, f"target {entry.target.qualname} dispatches: {why}"
        return True, (
            f"dynamic target {entry.target_text!r} — unresolvable "
            "statically, conservatively dispatch-capable"
        )
