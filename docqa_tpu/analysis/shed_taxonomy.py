"""shed-taxonomy: every request-path raise is a LEDGERED typed shed.

The serving chain's failure surface is a closed taxonomy
(``shed_taxonomy.json``): each shed class carries its declared HTTP
status, cost-ledger outcome, and trace flag in ONE reviewed file — the
same file docs/OPERATIONS.md renders and tests/test_serve_wiring.py
exercises end-to-end.  Three sub-rules hold the tree to it:

1. **unledgered raise** — every ``raise`` in a function reachable from
   :data:`~docqa_tpu.analysis.deadline_flow.REQUEST_PATH_MODULES` (BFS
   over the package call graph via the chassis' ``resolve_call``) must
   name a ledgered class.  Bare ``Exception``/``RuntimeError``/
   ``BaseException``/``TimeoutError`` raises are findings — an operator
   cannot retry/alert on a generic error; validation builtins
   (``ValueError``, ``TypeError``, ...) are programming-error raises,
   not sheds, and pass.  Re-raises (``raise`` / ``raise e`` from an
   except binding / ``raise x.error``) propagate an already-typed error
   and pass; so does raising a helper call whose arguments name a
   ledgered class (the ``raise self._shed(req, kind, QueueFull(...))``
   idiom — the helper retires the cost record, the typed instance rides
   through).
2. **undeclared / stale taxonomy** — every package exception class whose
   base chain reaches a ledgered class must itself be ledgered (a new
   ``QueueFull`` subclass silently inherits a 503 mapping but NOT its
   cost outcome — declaring it is the point), and every ledger entry
   must still name a class defined in its declared module (stale
   entries fail, PR-3 style).  Entries must carry ``http_status``,
   ``cost_outcome``, and ``trace_flag``.
3. **subtype swallow** — an ``except C`` handler on the request path
   that catches a ledgered class whose ledgered SUBCLASS declares a
   *different* HTTP status loses that subtype's contract (catch
   ``TimeoutError`` and map it to one status while ``DeadlineExceeded``
   is 504 and ``ResultTimeout`` degrades to 200) — unless an earlier
   handler in the same try already caught the subtype, or the handler
   re-raises.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
    stmt_walk,
)
from docqa_tpu.analysis.deadline_flow import REQUEST_PATH_MODULES

LEDGER_NAME = "shed_taxonomy.json"

# builtin raises that are programming-error/validation contracts, not
# load sheds — an /ask caller never sees these as a typed 5xx story
_VALIDATION_BUILTINS = frozenset(
    {
        "ValueError", "TypeError", "KeyError", "IndexError",
        "AttributeError", "NotImplementedError", "AssertionError",
        "StopIteration", "StopAsyncIteration", "FileNotFoundError",
        "OSError", "IOError", "GeneratorExit", "KeyboardInterrupt",
    }
)

# raising one of these bare is ALWAYS a finding on the request path:
# the operator story ("retry? alert? page?") needs a taxonomy type
_BARE_GENERICS = frozenset(
    {"Exception", "RuntimeError", "BaseException", "TimeoutError"}
)


def default_ledger_path() -> str:
    """The checked-in taxonomy: ``<repo>/shed_taxonomy.json``."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), LEDGER_NAME)


def _package_ledger_path(package: Package) -> Optional[str]:
    """Ledger next to the analyzed package's root (fixture trees carry
    their own or none; the real runs resolve to the repo's)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            cand = os.path.join(os.path.dirname(base), LEDGER_NAME)
            if os.path.exists(cand):
                return cand
            cand = os.path.join(base, LEDGER_NAME)
            if os.path.exists(cand):
                return cand
    return None


def load_ledger(path: Optional[str]) -> Dict:
    if not path or not os.path.exists(path):
        return {"sheds": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("sheds", {})
    return data


def request_path_functions(package: Package) -> Set[int]:
    """id()s of every function reachable from a request-path module via
    the chassis call resolution (BFS; unresolvable calls simply don't
    extend the frontier — same no-guess contract as resolve_call)."""
    reachable: Dict[int, FunctionInfo] = {}
    frontier: List[FunctionInfo] = []
    for fn in package.functions:
        if (
            fn.module.name in REQUEST_PATH_MODULES
            or fn.module.request_path_pragma
        ):
            reachable[id(fn)] = fn
            frontier.append(fn)
    while frontier:
        fn = frontier.pop()
        for node in stmt_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = package.resolve_call(fn, node)
            if callee is not None and id(callee) not in reachable:
                reachable[id(callee)] = callee
                frontier.append(callee)
    return set(reachable)


class ShedTaxonomyChecker:
    rule = "shed-taxonomy"

    def __init__(self, ledger_path: Optional[str] = None):
        self._ledger_path = ledger_path

    def check(self, package: Package) -> List[Finding]:
        path = (
            self._ledger_path
            or _package_ledger_path(package)
            or default_ledger_path()
        )
        ledger = load_ledger(path)
        sheds: Dict[str, Dict] = ledger.get("sheds", {})
        out: List[Finding] = []
        class_defs = self._class_defs(package)
        out.extend(self._check_ledger(package, sheds, class_defs))
        out.extend(self._check_subclasses(sheds, class_defs))
        reachable = request_path_functions(package)
        for fn in package.functions:
            if id(fn) not in reachable:
                continue
            out.extend(self._check_raises(fn, sheds))
            out.extend(self._check_handlers(fn, sheds, class_defs))
        return out

    # -- ledger integrity -----------------------------------------------------

    @staticmethod
    def _class_defs(
        package: Package,
    ) -> Dict[str, Tuple[str, int, str, List[str]]]:
        """name -> (module_name, lineno, relpath, base names)."""
        defs: Dict[str, Tuple[str, int, str, List[str]]] = {}
        for module in package.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                bases = [
                    dotted_name(b).rsplit(".", 1)[-1]
                    for b in node.bases
                    if dotted_name(b)
                ]
                defs[node.name] = (
                    module.name, node.lineno, module.relpath, bases,
                )
        return defs

    def _check_ledger(
        self, package: Package, sheds: Dict[str, Dict], class_defs
    ) -> List[Finding]:
        """Stale entries (declared class gone from its module) and
        malformed entries (missing status/outcome/flag).  Staleness only
        fires when the declaring module is in THIS package — the gate
        runs per-root (docqa_tpu, scripts) and the scripts pass must not
        report the whole taxonomy stale."""
        out: List[Finding] = []
        module_names = {m.name for m in package.modules}
        by_name = {m.name: m for m in package.modules}
        for name, entry in sorted(sheds.items()):
            declared_module = entry.get("module", "")
            if declared_module not in module_names:
                continue
            module = by_name[declared_module]
            defined = class_defs.get(name)
            if defined is None or defined[0] != declared_module:
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        1,
                        "<ledger>",
                        f"stale shed_taxonomy entry: class {name} is not "
                        f"defined in {declared_module}",
                    )
                )
                continue
            missing = [
                k
                for k in ("http_status", "cost_outcome", "trace_flag")
                if k not in entry
            ]
            if missing:
                out.append(
                    Finding(
                        self.rule,
                        defined[2],
                        defined[1],
                        name,
                        f"shed_taxonomy entry for {name} is missing "
                        f"{', '.join(missing)}",
                    )
                )
        return out

    def _ledger_bases(
        self, name: str, sheds: Dict[str, Dict], class_defs
    ) -> Set[str]:
        """Transitive base-name closure of a class, through both the
        package class defs and the ledger's declared bases."""
        seen: Set[str] = set()
        frontier = [name]
        while frontier:
            n = frontier.pop()
            bases: List[str] = []
            if n in class_defs:
                bases.extend(class_defs[n][3])
            if n in sheds:
                bases.extend(sheds[n].get("bases", []))
            for b in bases:
                if b not in seen:
                    seen.add(b)
                    frontier.append(b)
        return seen

    def _check_subclasses(
        self, sheds: Dict[str, Dict], class_defs
    ) -> List[Finding]:
        """A package class subclassing a ledgered shed must be ledgered
        itself — subtypes inherit the except-site mapping but not the
        declared outcome/flag, so every one is a taxonomy decision."""
        out: List[Finding] = []
        if not sheds:
            return out
        for name, (mod, lineno, relpath, _bases) in sorted(
            class_defs.items()
        ):
            if name in sheds:
                continue
            chain = self._ledger_bases(name, sheds, class_defs)
            hit = sorted(chain & set(sheds))
            if hit:
                out.append(
                    Finding(
                        self.rule,
                        relpath,
                        lineno,
                        name,
                        f"typed shed {name} (subclass of {hit[0]}) is not "
                        "declared in shed_taxonomy.json",
                    )
                )
        return out

    # -- raise sites ----------------------------------------------------------

    @staticmethod
    def _raised_class(node: ast.Raise) -> Optional[str]:
        """Syntactic class name of a raise, or None when the raised
        value is dynamic (re-raised binding, stored error object,
        lowercase helper call)."""
        exc = node.exc
        if exc is None:
            return None  # bare re-raise
        if isinstance(exc, ast.Call):
            name = call_name(exc)
        else:
            name = dotted_name(exc)
        if not name:
            return None
        tail = name.rsplit(".", 1)[-1]
        if not tail or not tail[0].isupper():
            return None  # helper call / variable — not a class name
        return tail

    def _check_raises(
        self, fn: FunctionInfo, sheds: Dict[str, Dict]
    ) -> List[Finding]:
        out: List[Finding] = []
        ledgered = set(sheds)
        for node in stmt_walk(fn.node):
            if not isinstance(node, ast.Raise):
                continue
            tail = self._raised_class(node)
            if tail is None:
                # dynamic raise: OK when any ledgered class name appears
                # in the expression (the `raise self._shed(..., QueueFull
                # (...))` idiom); a fully opaque expression is a re-raise
                # of a stored/bound error and passes
                continue
            if tail in ledgered or tail in _VALIDATION_BUILTINS:
                continue
            if isinstance(node.exc, ast.Call):
                arg_names = {
                    n
                    for a in list(node.exc.args)
                    + [kw.value for kw in node.exc.keywords]
                    for n in (
                        dotted_name(c).rsplit(".", 1)[-1]
                        for c in ast.walk(a)
                        if isinstance(c, (ast.Name, ast.Attribute))
                    )
                    if n
                }
                if arg_names & ledgered:
                    continue  # wraps/forwards a ledgered instance
            if tail in _BARE_GENERICS:
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        node.lineno,
                        fn.qualname,
                        f"bare {tail} raised on the request path — raise "
                        "a typed shed declared in shed_taxonomy.json",
                    )
                )
            else:
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        node.lineno,
                        fn.qualname,
                        f"{tail} raised on the request path is not "
                        "declared in shed_taxonomy.json",
                    )
                )
        return out

    # -- catch sites ----------------------------------------------------------

    @staticmethod
    def _handler_names(handler: ast.ExceptHandler) -> List[str]:
        t = handler.type
        if t is None:
            return []
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        return [
            dotted_name(e).rsplit(".", 1)[-1]
            for e in elts
            if dotted_name(e)
        ]

    def _check_handlers(
        self, fn: FunctionInfo, sheds: Dict[str, Dict], class_defs
    ) -> List[Finding]:
        out: List[Finding] = []
        for node in stmt_walk(fn.node):
            if not isinstance(node, ast.Try):
                continue
            caught_earlier: Set[str] = set()
            for handler in node.handlers:
                names = self._handler_names(handler)
                reraises = any(
                    isinstance(n, ast.Raise)
                    for n in ast.walk(handler)
                )
                for cname in names:
                    if cname in sheds and not reraises:
                        c_status = sheds[cname].get("http_status")
                        for sname, sentry in sorted(sheds.items()):
                            if sname == cname or sname in caught_earlier:
                                continue
                            if cname not in self._ledger_bases(
                                sname, sheds, class_defs
                            ):
                                continue
                            if sentry.get("http_status") == c_status:
                                continue
                            out.append(
                                Finding(
                                    self.rule,
                                    fn.module.relpath,
                                    handler.lineno,
                                    fn.qualname,
                                    f"except {cname} swallows subtype "
                                    f"{sname} (declared status "
                                    f"{sentry.get('http_status')} != "
                                    f"{c_status}) — catch the subtype "
                                    "first or re-raise",
                                )
                            )
                caught_earlier.update(names)
        return out
