"""spec-shape: a PartitionSpec's axis count must match the array's rank.

A ``PartitionSpec`` with k entries annotates exactly a rank-k array; GSPMD
rejects a mismatch only at lowering time, on whatever mesh first compiles
the spec — which for the literal-shaped parameter tables means a broken
spec edit sits undetected until the next sharded run (and on a 1x1 dev
box, forever).  The shapes and the specs live in DIFFERENT modules by
design (``models/decoder.py`` owns ``decoder_param_schema``;
``parallel/sharding.py`` owns ``decoder_param_pspecs``/``cache_pspecs``),
so nothing structural keeps them in sync — this rule does.

Resolution model: the checker cross-references two kinds of package-wide
**name-template facts** (f-string names are normalized, ``f"l{i}_wq"`` ->
``l{}_wq``, so schema and spec rows written as parallel f-strings match):

* **rank facts** — ``(name, ..., (shape, tuple), ...)`` rows yielded by
  ``*schema*`` generator functions (the shape is the unique literal-tuple
  element), and ``d[f"k{i}"] = jnp.zeros(shape, ...)`` subscript stores
  whose shape resolves to a literal tuple (directly or through one local
  assignment).
* **spec facts** — dict-literal entries, ``dict.update({...})`` rows and
  subscript stores whose value is a ``PartitionSpec``/``P`` call (or a
  local name assigned from one): the fact is ``len(args)``.

A template with consistent rank facts and a spec fact of a different
arity flags at the spec site.  Templates with conflicting rank facts
(same name, different literal ranks anywhere in the package) are dropped
— ambiguity never guesses.  ``P()`` (fully replicated) matches any rank.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
)


def _name_template(node: ast.AST) -> Optional[str]:
    """Literal or f-string key -> template ("l{}_wq"); None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            elif isinstance(v, ast.FormattedValue):
                parts.append("{}")
            else:
                return None
        return "".join(parts)
    return None


def _is_pspec_call(fn: FunctionInfo, node: ast.AST) -> Optional[ast.Call]:
    if not isinstance(node, ast.Call):
        return None
    resolved = fn.module.resolve_alias(call_name(node))
    if resolved.rsplit(".", 1)[-1] == "PartitionSpec":
        return node
    return None


def _spec_arity(call: ast.Call) -> Optional[int]:
    """len(P(...)) — None for P(*xs) or P() (replicated matches any)."""
    if any(isinstance(a, ast.Starred) for a in call.args):
        return None
    if call.keywords or not call.args:
        return None
    return len(call.args)


_SHAPED_CTORS = frozenset({"zeros", "ones", "full", "empty", "normal"})


class SpecShapeChecker:
    rule = "spec-shape"

    def check(self, package: Package) -> List[Finding]:
        ranks = self._rank_facts(package)
        out: List[Finding] = []
        for fn in package.functions:
            for template, arity, node in self._spec_facts(fn):
                rank = ranks.get(template)
                if rank is None or arity is None or rank < 0:
                    continue
                if rank != arity:
                    out.append(
                        Finding(
                            self.rule,
                            fn.module.relpath,
                            getattr(node, "lineno", 1),
                            fn.qualname,
                            f"PartitionSpec for '{template}' has {arity} "
                            f"entries but the array is rank {rank} "
                            f"(shape declared elsewhere in the package)",
                        )
                    )
        return out

    # -- rank facts -----------------------------------------------------------

    def _rank_facts(self, package: Package) -> Dict[str, int]:
        """template -> rank; conflicting templates collapse to -1."""
        ranks: Dict[str, int] = {}

        def record(template: Optional[str], rank: Optional[int]) -> None:
            if template is None or rank is None:
                return
            old = ranks.get(template)
            if old is None:
                ranks[template] = rank
            elif old != rank:
                ranks[template] = -1  # ambiguous: never checked

        for fn in package.functions:
            lits = self._literal_tuples(fn.node)
            for node in ast.walk(fn.node):
                # schema rows: yield (name, ..., (a, b), ...)
                if isinstance(node, ast.Yield) and isinstance(
                    node.value, ast.Tuple
                ):
                    elts = node.value.elts
                    template = _name_template(elts[0]) if elts else None
                    tuples = [
                        e for e in elts[1:] if isinstance(e, ast.Tuple)
                    ]
                    if template is not None and len(tuples) == 1:
                        record(template, len(tuples[0].elts))
                # d[f"k{i}"] = jnp.zeros(shape, ...)
                elif isinstance(node, ast.Assign) and len(
                    node.targets
                ) == 1 and isinstance(node.targets[0], ast.Subscript):
                    template = _name_template(node.targets[0].slice)
                    rank = self._ctor_rank(node.value, lits)
                    record(template, rank)
        return ranks

    def _ctor_rank(
        self, value: ast.AST, lits: Dict[str, int]
    ) -> Optional[int]:
        if not isinstance(value, ast.Call):
            return None
        tail = call_name(value).rsplit(".", 1)[-1]
        if tail not in _SHAPED_CTORS:
            return None
        shape = value.args[0] if value.args else None
        if isinstance(shape, ast.Tuple):
            if any(isinstance(e, ast.Starred) for e in shape.elts):
                return None
            return len(shape.elts)
        if isinstance(shape, ast.Name):
            return lits.get(shape.id)
        return None

    @staticmethod
    def _literal_tuples(scope: ast.AST) -> Dict[str, int]:
        """name -> rank for ``shape = (a, b, c)`` local assignments."""
        out: Dict[str, int] = {}
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Tuple
            ) and not any(
                isinstance(e, ast.Starred) for e in node.value.elts
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = len(node.value.elts)
        return out

    # -- spec facts -----------------------------------------------------------

    def _spec_facts(self, fn: FunctionInfo):
        """Yield (template, arity, site-node) for every name -> P(...)
        association in ``fn``."""
        # local names bound to a P(...) call: spec = P(a, None, b, None)
        local_specs: Dict[str, int] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                call = _is_pspec_call(fn, node.value)
                if call is not None:
                    arity = _spec_arity(call)
                    if arity is not None:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local_specs[t.id] = arity

        def value_arity(value: ast.AST) -> Optional[int]:
            call = _is_pspec_call(fn, value)
            if call is not None:
                return _spec_arity(call)
            if isinstance(value, ast.Name):
                return local_specs.get(value.id)
            return None

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if k is None:
                        continue
                    template = _name_template(k)
                    arity = value_arity(v)
                    if template is not None and arity is not None:
                        yield template, arity, k
            elif isinstance(node, ast.Assign) and len(
                node.targets
            ) == 1 and isinstance(node.targets[0], ast.Subscript):
                template = _name_template(node.targets[0].slice)
                arity = value_arity(node.value)
                if template is not None and arity is not None:
                    yield template, arity, node
