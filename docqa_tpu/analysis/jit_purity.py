"""jit-purity: no Python side effects or host syncs inside traced code.

A function compiled via ``jax.jit`` / ``pjit`` / ``shard_map`` runs its
Python body ONCE, at trace time; any side effect in it (metrics counter,
lock acquisition, ``print``, ``time.*``) silently executes on the wrong
schedule — once per compile instead of once per call — and any host sync
(``np.asarray``/``.item()``/``float(tracer)``) either fails under tracing
or forces a device round-trip that defeats the compiled pipeline.

The checker finds traced **roots**:

* ``@jax.jit`` / ``@functools.partial(jax.jit, …)`` decorators,
* ``jax.jit(f)`` / ``pjit(f)`` / ``shard_map(f, …)`` call sites, where
  ``f`` is a bare name (module or nested function), ``self.method``,
  ``functools.partial(g, …)`` (recursing to ``g``), or a ``lambda``
  (its body is scanned in place, and package calls inside it widen the
  closure),

then takes the transitive closure over package-resolvable calls (a
function *called from* traced code is traced too), and flags in every
traced function:

* ``print(…)``, ``log.…``/``logging.…`` calls, ``span(…)``;
* ``time.…`` calls (through import aliases);
* metrics-registry traffic (any call chain through a ``…registry…``
  object, or ``.inc(…)``/``.observe(…)``);
* lock traffic: ``with`` on / ``.acquire()`` of a lock-ish attribute
  (``…_lock``/``…_cv``/``…lock``);
* host-sync escapes: ``np.asarray``/``np.array``/``np.copy``,
  ``.item()``/``.tolist()``, ``jax.device_get``, and ``float()``/
  ``int()``/``bool()`` applied directly to a traced-function parameter;
* ``faults.perturb(…)`` (fault injection is host-side by definition);
* ``open(…)`` and ``global`` statements (IO / mutable-global capture).

Findings attribute the side effect to the function it appears in; when
that function was reached transitively the message names the jit root.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name as call_name_of,
)

JIT_WRAPPERS = frozenset({"jit", "pjit", "shard_map"})
LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|cv|mutex|rlock)$|_lock$|_cv$")
REGISTRY_RE = re.compile(r"registry", re.IGNORECASE)
HOST_SYNC_CALLS = frozenset(
    {
        "numpy.asarray",
        "numpy.array",
        "numpy.copy",
        "jax.device_get",
    }
)


def _is_jit_wrapper(module, node: ast.AST) -> bool:
    """True for expressions naming jax.jit / pjit / shard_map (through
    import aliases), including ``functools.partial(jax.jit, ...)``."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_jit_wrapper(module, node.args[0])
        return False
    if isinstance(node, (ast.Name, ast.Attribute)):
        dotted = module.resolve_alias(call_name_of(node))
        return dotted.rsplit(".", 1)[-1] in JIT_WRAPPERS
    return False


def discover_jit_roots(
    package: Package,
) -> Tuple[Dict[int, Tuple[FunctionInfo, str]], List[Tuple[FunctionInfo, ast.Lambda, str]]]:
    """Direct traced roots of a package: functions/lambdas handed to
    ``jax.jit``/``pjit``/``shard_map`` (decorators, call sites, partials,
    builder returns, module-level assignments).  Returns ``(roots,
    lambdas)`` keyed/labelled exactly the way :class:`JitPurityChecker`
    consumes them; the sharding audit (``analysis/shard_audit.py``) reuses
    this enumeration so its ``shard_budget.json`` root ledger can never
    drift from what jit-purity considers traced."""
    checker = JitPurityChecker()
    traced: Dict[int, Tuple[FunctionInfo, str]] = {}
    lambdas: List[Tuple[FunctionInfo, ast.Lambda, str]] = []

    def mark(fn: Optional[FunctionInfo], via: str) -> None:
        if fn is None or id(fn.node) in traced:
            return
        traced[id(fn.node)] = (fn, via)

    for fn in package.functions:
        node = fn.node
        for dec in getattr(node, "decorator_list", ()):
            if _is_jit_wrapper(fn.module, dec) or (
                isinstance(dec, ast.Call)
                and _is_jit_wrapper(fn.module, dec.func)
            ):
                mark(fn, "")
    for fn in package.functions:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = fn.module.resolve_alias(name).rsplit(".", 1)[-1]
            if tail not in JIT_WRAPPERS or not node.args:
                continue
            checker._mark_target(
                package, fn, node.args[0], mark, lambdas, via=""
            )
    # module-level jit call sites (fn = jax.jit(kernel) at top level)
    for module in package.modules:
        scope = FunctionInfo(
            module=module, node=module.tree, qualname="<module>",
            class_name=None,
        )
        stack = list(ast.iter_child_nodes(module.tree))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue  # per-function pass covers these
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            tail = module.resolve_alias(name).rsplit(".", 1)[-1]
            if tail not in JIT_WRAPPERS or not node.args:
                continue
            checker._mark_target(
                package, scope, node.args[0], mark, lambdas, via=""
            )
    return traced, lambdas


class JitPurityChecker:
    rule = "jit-purity"

    def check(self, package: Package) -> List[Finding]:
        # function identity -> reason text ("" for direct roots)
        traced, lambdas = discover_jit_roots(package)

        def mark(fn: Optional[FunctionInfo], via: str) -> None:
            if fn is None or id(fn.node) in traced:
                return
            traced[id(fn.node)] = (fn, via)

        # -- pass 2: transitive closure over package calls --------------------
        # lambdas participate: their bodies resolve in the enclosing
        # function's scope, including `name = functools.partial(f, …)`
        # local aliases (the GenerateEngine spec-decode idiom)
        frontier: List[Tuple[FunctionInfo, str, ast.AST]] = [
            (fn, via, fn.node) for fn, via in traced.values()
        ]
        frontier.extend(
            (fn, via or f"{fn.qualname}.<lambda>", lam)
            for fn, lam, via in lambdas
        )
        while frontier:
            fn, via, body = frontier.pop()
            root = via or fn.qualname
            for node in ast.walk(body):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                tail = fn.module.resolve_alias(name).rsplit(".", 1)[-1]
                if tail in JIT_WRAPPERS:
                    continue  # jit wrapper calls inside traced code
                callee = package.resolve_call(fn, node)
                if callee is None and name and "." not in name:
                    callee = self._partial_alias(package, fn, name)
                if callee is not None and id(callee.node) not in traced:
                    traced[id(callee.node)] = (callee, root)
                    frontier.append((callee, root, callee.node))

        # -- pass 3: scan every traced body -----------------------------------
        out: List[Finding] = []
        for fn, via in traced.values():
            out.extend(self._scan(fn, fn.node, via))
        for fn, lam, via in lambdas:
            out.extend(self._scan(fn, lam, via or f"{fn.qualname}.<lambda>"))
        return out

    def _partial_alias(self, package, fn, name: str):
        """Resolve a bare call through a local ``name = functools.partial(
        target, …)`` (or ``name = target``) assignment in the caller."""
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            ):
                continue
            value = node.value
            if (
                isinstance(value, ast.Call)
                and call_name(value).rsplit(".", 1)[-1] == "partial"
                and value.args
            ):
                value = value.args[0]
            fake = ast.Call(func=value, args=[], keywords=[])
            ast.copy_location(fake, value)
            resolved = package.resolve_call(fn, fake)
            if resolved is not None:
                return resolved
        return None

    def _mark_target(
        self, package, fn, target, mark, lambdas, via: str, depth: int = 0
    ) -> None:
        """Resolve the first argument of a jit/shard_map call."""
        if depth > 6:
            return
        if isinstance(target, ast.Lambda):
            # dedupe by node identity: two call sites wrapping the same
            # builder (e.g. the serving engine AND an audit harness both
            # jit build_fused_search_program's return) must not ledger
            # the one lambda twice
            if not any(lam is target for _fn, lam, _via in lambdas):
                lambdas.append((fn, target, via))
            return
        if isinstance(target, ast.Call):
            name = call_name(target)
            if name.rsplit(".", 1)[-1] == "partial" and target.args:
                self._mark_target(
                    package, fn, target.args[0], mark, lambdas, via,
                    depth + 1,
                )
            elif name.rsplit(".", 1)[-1] in JIT_WRAPPERS and target.args:
                # jax.jit(shard_map(body, ...))
                self._mark_target(
                    package, fn, target.args[0], mark, lambdas, via,
                    depth + 1,
                )
            else:
                # jax.jit(build_x_program(...)): a package builder whose
                # RETURN VALUE is the traced callable — mark every nested
                # def/lambda its OWN return statements hand back
                # (stmt_walk: returns of helpers nested in the builder
                # belong to those helpers, not to the builder)
                builder = package.resolve_call(fn, target)
                if builder is not None:
                    from docqa_tpu.analysis.core import stmt_walk

                    for stmt in stmt_walk(builder.node):
                        if isinstance(stmt, ast.Return) and (
                            stmt.value is not None
                        ):
                            self._mark_target(
                                package, builder, stmt.value, mark,
                                lambdas, via or builder.qualname,
                                depth + 1,
                            )
            return
        fake_call = ast.Call(func=target, args=[], keywords=[])
        ast.copy_location(fake_call, target)
        mark(package.resolve_call(fn, fake_call), via)

    # -- body scan ------------------------------------------------------------

    def _scan(self, fn: FunctionInfo, body: ast.AST, via: str) -> List[Finding]:
        module = fn.module
        out: List[Finding] = []
        suffix = f" [traced via {via}]" if via else ""
        if isinstance(body, ast.Lambda):
            params = {a.arg for a in body.args.args}
        elif hasattr(fn.node, "args"):
            params = set(fn.params)
        else:  # module-scope pseudo-function
            params = set()

        def add(node: ast.AST, what: str) -> None:
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    fn.qualname,
                    f"{what} inside jit-traced code{suffix}",
                )
            )

        # don't descend into nested defs/lambdas here: nested defs inside a
        # traced function ARE traced (closure), so do descend — but a
        # nested def containing its own jit wrapping was marked already.
        for node in ast.walk(body):
            if isinstance(node, ast.Global):
                add(node, "global-statement (mutable global capture)")
                continue
            if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
                for item in node.items:
                    text = call_name_of(item.context_expr)
                    if not text and isinstance(item.context_expr, ast.Call):
                        text = call_name(item.context_expr)
                    attr = text.rsplit(".", 1)[-1] if text else ""
                    if attr and LOCKISH_RE.search(attr):
                        add(node, f"lock acquisition ('with {text}')")
                continue
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            resolved = module.resolve_alias(name)
            head = resolved.split(".")[0]
            attr = name.rsplit(".", 1)[-1]
            if name == "print":
                add(node, "print()")
            elif head == "time" and "." in resolved:
                add(node, f"{resolved}() (host clock/sleep)")
            elif head == "logging" or name.split(".")[0] in ("log", "logger"):
                add(node, f"logging call {name}()")
            elif attr == "perturb":
                add(node, "faults.perturb() (fault-injection hook)")
            elif name == "span" or resolved.endswith("metrics.span"):
                add(node, "span() (metrics/tracing context)")
            elif attr in ("inc", "observe") or (
                "." in name
                and REGISTRY_RE.search(name.rsplit(".", 1)[0])
                and attr in ("counter", "histogram", "gauge")
            ):
                add(node, f"metrics call {name}()")
            elif attr == "acquire" and LOCKISH_RE.search(
                name.rsplit(".", 2)[-2] if name.count(".") >= 1 else ""
            ):
                add(node, f"lock acquisition ({name}())")
            elif resolved in HOST_SYNC_CALLS or attr in ("item", "tolist"):
                add(node, f"host-sync escape {name}()")
            elif name == "open":
                add(node, "open() (file IO)")
            elif name in ("float", "int", "bool") and node.args:
                a = node.args[0]
                if isinstance(a, ast.Name) and a.id in params:
                    add(
                        node,
                        f"{name}() on a traced argument (host-sync escape)",
                    )
        return out
