"""docqa-numcheck Tier B: drive the canonical serving workloads under a
compile-counting hook, AOT-measure every root's HBM footprint, and hold
both to a checked-in budget.

The shard audit (``analysis/shard_audit.py``) proves each program's
COLLECTIVE content; this module proves two different compilation-class
contracts the ROADMAP previously enforced only by convention:

* **compile counts** — every jit root's admitted shape set is warmed
  ahead of the serving path, and a repeated steady-state round performs
  ZERO retraces.  The batcher's two-shape admission policy
  (``serve._admit_round``: 4-lane trickle + full ``n_slots`` per prompt
  bucket) is driven explicitly, so the exact compile count per root is a
  checked-in number (``compile_budget.json``) and a new shape sneaking
  into the serving path flips CI red instead of adding a silent
  multi-second compile to someone's request.
* **HBM budgets** — each root is AOT-lowered (``lower().compile()``)
  and its ``memory_analysis()`` bytes (argument/output/temp/generated
  code) recorded; per-root peak bytes gate against a budget CEILING.
  ``--write-budget`` preserves an existing ceiling when the measurement
  still fits and stamps any GROWTH with a ``TODO`` note the gate rejects
  until a human justifies it — regeneration cannot launder a memory
  regression, mirroring ``shard_audit``'s semantic-invariant design.

Workloads (tiny configs, CPU-lowerable in seconds):

* ``serve``          — decoder prefill across every admitted shape
  (both batch families x every bucket) + the decode chunk, through a
  real :class:`~docqa_tpu.engines.serve.ContinuousBatcher` (warmup, then
  a trickle round and a full round as the steady state);
* ``generate``       — the solo engine's fused prefill+decode program;
* ``retrieve_fused`` — the single-dispatch text→top-k program;
* ``seq2seq``        — the BART-class summarize program;
* ``encoder``        — the batched document/query encoder.

The budget also carries the same **jit-root ledger** as the shard
budget (enumerated by jit-purity's discovery pass): every traced root
must be covered by a workload or waived with a reason, so a new
``jax.jit`` site fails the gate until its compile story is stated.

Violations are re-derived from the MEASUREMENT (``semantic_violations``)
so an "accept whatever it prints" budget update still cannot admit a
steady-state retrace, a missing shape family, or a trickle shape that
stopped being cheaper than the full width.

Entry points: ``scripts/compile_audit.py`` (CLI; CI uploads its
``--report`` JSON as the compile/HBM trend artifact) and ``pytest -m
lint`` (tests/test_compile_audit.py).  docs/STATIC_ANALYSIS.md documents
the budget format and amendment workflow.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence

# one byte-accounting implementation, shared with the serving layer
# (GenerateEngine.decode_memory_analysis) — it lives in utils because
# engines must never import the lint tree
from docqa_tpu.utils import compiled_memory_stats as memory_of

WORKLOADS = ("serve", "generate", "retrieve_fused", "seq2seq", "encoder")

# headroom factor applied when a ceiling must grow (or is first written):
# measured bytes wobble a few percent across jaxlib versions; a regression
# worth gating is a structural one (a materialized tree, a doubled cache)
CEILING_HEADROOM = 1.25


def default_budget_path() -> str:
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), "compile_budget.json")


# ---------------------------------------------------------------------------
# counting + memory helpers
# ---------------------------------------------------------------------------


def jit_cache_size(fn) -> int:
    """Compiled-specialization count of a ``jax.jit`` wrapper — the
    compile-counting hook.  One entry per traced (shape, dtype, sharding,
    static-args) signature, so a steady-state round that grows it by N
    performed exactly N retraces."""
    size = getattr(fn, "_cache_size", None)
    if size is None:  # pragma: no cover - jax pinned in CI
        raise RuntimeError(
            "jax.jit wrapper has no _cache_size(); the compile audit "
            "needs it (jax>=0.4.31)"
        )
    return int(size())


def lowered_memory(fn, *args, **kwargs) -> Optional[Dict[str, int]]:
    """AOT memory accounting for one root, augmented with the compiled
    program's ``cost_analysis()`` FLOPs / bytes-accessed — the cost
    model every ledgered jit root now carries (docqa-observatory).  The
    GATE stays compile-count/bytes-based; the cost columns are
    informational (they feed the same per-program accounting the
    dispatch spine's MFU attribution uses at runtime)."""
    from docqa_tpu.obs.observatory import parse_cost_analysis

    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception:
        return None
    out = memory_of(compiled)
    cost = parse_cost_analysis(compiled)
    if cost is not None:
        # backends without the estimate keep bytes-only rows
        out = dict(out or {})
        out.update(cost)
    return out


# ---------------------------------------------------------------------------
# audit configs (tiny: every workload lowers AND runs in seconds on CPU)
# ---------------------------------------------------------------------------


def _audit_decoder_cfg():
    from docqa_tpu.config import DecoderConfig

    return DecoderConfig(
        vocab_size=64,
        hidden_dim=32,
        num_layers=2,
        num_heads=2,
        num_kv_heads=2,
        head_dim=16,
        mlp_dim=64,
        max_seq_len=128,
    )


def _audit_gen_cfg():
    from docqa_tpu.config import GenerateConfig

    return GenerateConfig(
        max_new_tokens=4,
        prefill_buckets=(16, 32),
        decode_chunk=4,
        max_concurrent=8,
    )


def _audit_encoder_cfg():
    from docqa_tpu.config import EncoderConfig

    return EncoderConfig(
        vocab_size=64,
        hidden_dim=32,
        num_layers=1,
        num_heads=2,
        mlp_dim=64,
        max_seq_len=16,
        embed_dim=32,
        dtype="float32",
    )


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------


def _audit_serve() -> Dict[str, Any]:
    """The PAGED batcher's whole compile surface: one ragged prefill
    program per packed token budget (<= 2) plus the one block-table
    decode chunk — the collapse from the pre-paged (2 shape families x
    prompt buckets) matrix that ROADMAP item 1 demanded.  Steady state =
    a trickle round (1 request) and a full round (n_slots requests) of
    MIXED prompt lengths AFTER warmup; both must hit warm programs (mixed
    lengths sharing one program is the point of ragged prefill)."""
    import jax
    import jax.numpy as jnp

    from docqa_tpu.engines.generate import GenerateEngine
    from docqa_tpu.engines.paged import kv_bytes_per_token
    from docqa_tpu.engines.serve import ContinuousBatcher

    cfg, gen = _audit_decoder_cfg(), _audit_gen_cfg()
    engine = GenerateEngine(cfg, gen)
    # cache_len 256: large enough that the 128-aligned prefix cache is
    # ENABLED (share_alignment < seq_capacity), so the warm prefill
    # program family is part of the audited surface
    batcher = ContinuousBatcher(engine, n_slots=8, chunk=4, cache_len=256)
    try:
        batcher.warmup()
        prefill_fn = batcher._get_prefill_fn()
        prefill_warm_fn = batcher._get_prefill_warm_fn()
        decode_fn = batcher._get_decode_fn()
        warm_prefill = jit_cache_size(prefill_fn)
        warm_prefill_w = jit_cache_size(prefill_warm_fn)
        warm_decode = jit_cache_size(decode_fn)

        # steady state: a trickle round, then a full round of MIXED
        # lengths (the shape-family x bucket matrix this would have
        # retraced across before paging), against warm programs
        batcher.submit_ids([1] * 10, max_new_tokens=3).result(timeout=120)
        handles = [
            batcher.submit_ids([1] * (4 + 5 * (i % 5)), max_new_tokens=3)
            for i in range(batcher.n_slots)
        ]
        for h in handles:
            h.result(timeout=120)
        # warm-prefix steady state: the same session key twice — the
        # second admission maps the cached prefix in and dispatches the
        # WARM program, which warmup must already have compiled
        warm_prompt = [1 + i % 60 for i in range(140)]
        batcher.submit_ids(
            warm_prompt + [3, 5], max_new_tokens=3, prefix_key="audit"
        ).result(timeout=120)
        batcher.submit_ids(
            warm_prompt + [7, 9], max_new_tokens=3, prefix_key="audit"
        ).result(timeout=120)
        retrace_prefill = jit_cache_size(prefill_fn) - warm_prefill
        retrace_prefill_w = (
            jit_cache_size(prefill_warm_fn) - warm_prefill_w
        )
        retrace_decode = jit_cache_size(decode_fn) - warm_decode

        # AOT memory per packed token budget (counting is done —
        # lowering can no longer pollute the numbers).  Shapes mirror
        # warmup() EXACTLY so expected_shapes can never drift from what
        # warmup compiles.
        S = batcher.n_slots
        pool_struct = {
            k: jax.ShapeDtypeStruct(v.shape, v.dtype)
            for k, v in batcher._pools.items()
        }
        spec_table = (
            jax.ShapeDtypeStruct((S, cfg.vocab_size), jnp.int32)
            if batcher.spec_k
            else None
        )
        rng = jax.random.PRNGKey(0)

        def prefill_mem(T: int, warm: bool = False):
            vec = lambda n: jax.ShapeDtypeStruct((n,), jnp.int32)  # noqa: E731
            packed = (vec(T), vec(T), vec(T), vec(T), vec(S), vec(S))
            if warm:
                tabs = jax.ShapeDtypeStruct(
                    (S, batcher.blocks_per_seq), jnp.int32
                )
                packed = packed + (tabs, vec(S))
                use = prefill_warm_fn
            else:
                use = prefill_fn
            packed = packed + (rng,)
            if batcher.spec_k:
                return lowered_memory(
                    use, engine.params, pool_struct, spec_table, *packed,
                )
            return lowered_memory(use, engine.params, pool_struct, *packed)

        per_shape = {
            f"tokens_{T}": prefill_mem(T) for T in batcher._token_buckets
        }
        per_shape_warm = {
            f"tokens_{T}": prefill_mem(T, warm=True)
            for T in batcher._token_buckets
        }
        tables = jax.ShapeDtypeStruct(
            (S, batcher.blocks_per_seq), jnp.int32
        )
        caps = jax.ShapeDtypeStruct((S,), jnp.int32)
        tok = jax.ShapeDtypeStruct((S,), jnp.int32)
        lens = jax.ShapeDtypeStruct((S,), jnp.int32)
        active = jax.ShapeDtypeStruct((S,), jnp.bool_)
        if batcher.spec_k:
            decode_mem = lowered_memory(
                decode_fn, engine.params, pool_struct, tables, caps,
                spec_table, tok, lens, active,
            )
        else:
            decode_mem = lowered_memory(
                decode_fn, engine.params, pool_struct, tables, caps,
                tok, lens, active, rng,
            )
        return {
            "meta": {
                "n_slots": S,
                "paged": True,
                "prefix_cache": batcher.prefix_cache_enabled,
                "token_buckets": list(batcher._token_buckets),
                "kv_block_size": batcher.block_size,
                "kv_pool_blocks": batcher.n_blocks,
                "kv_bytes_per_token": kv_bytes_per_token(cfg),
                "kv_pool_bytes": (
                    batcher.n_blocks * batcher.block_size
                    * kv_bytes_per_token(cfg)
                ),
            },
            "roots": {
                "serve_prefill": {
                    "compiles": warm_prefill,
                    "expected_shapes": len(batcher._token_buckets),
                    "steady_state_retraces": retrace_prefill,
                    "per_shape": per_shape,
                    "peak_bytes": max(
                        (m or {}).get("peak_bytes", 0)
                        for m in per_shape.values()
                    ),
                    # cost model (informational; gate stays bytes-based)
                    "flops": max(
                        (m or {}).get("flops", 0)
                        for m in per_shape.values()
                    ),
                    "bytes_accessed": max(
                        (m or {}).get("bytes_accessed", 0)
                        for m in per_shape.values()
                    ),
                },
                "serve_prefill_warm": {
                    "compiles": warm_prefill_w,
                    "expected_shapes": len(batcher._token_buckets),
                    "steady_state_retraces": retrace_prefill_w,
                    "per_shape": per_shape_warm,
                    "peak_bytes": max(
                        (m or {}).get("peak_bytes", 0)
                        for m in per_shape_warm.values()
                    ),
                    "flops": max(
                        (m or {}).get("flops", 0)
                        for m in per_shape_warm.values()
                    ),
                    "bytes_accessed": max(
                        (m or {}).get("bytes_accessed", 0)
                        for m in per_shape_warm.values()
                    ),
                },
                "serve_decode": {
                    "compiles": warm_decode,
                    "expected_shapes": 1,
                    "steady_state_retraces": retrace_decode,
                    "memory": decode_mem,
                    "peak_bytes": (decode_mem or {}).get("peak_bytes", 0),
                    "flops": (decode_mem or {}).get("flops", 0),
                    "bytes_accessed": (
                        (decode_mem or {}).get("bytes_accessed", 0)
                    ),
                },
            },
        }
    finally:
        batcher.stop()


def _audit_generate() -> Dict[str, Any]:
    from docqa_tpu.engines.generate import GenerateEngine

    cfg, gen = _audit_decoder_cfg(), _audit_gen_cfg()
    engine = GenerateEngine(cfg, gen)
    engine.generate_ids([[1, 2, 3]], max_new_tokens=4)
    warm = sum(jit_cache_size(fn) for fn in engine._fns.values())
    engine.generate_ids([[1, 2, 3]], max_new_tokens=4)
    after = sum(jit_cache_size(fn) for fn in engine._fns.values())
    mem = engine.decode_memory_analysis(prompt_len=3, max_new_tokens=4)
    return {
        "meta": {"programs": len(engine._fns)},
        "roots": {
            "generate_decode": {
                "compiles": warm,
                "expected_shapes": 1,
                "steady_state_retraces": after - warm,
                "memory": mem,
                "peak_bytes": (mem or {}).get("peak_bytes", 0),
                "flops": (mem or {}).get("flops", 0),
                "bytes_accessed": (mem or {}).get("bytes_accessed", 0),
            }
        },
    }


def _audit_retrieve() -> Dict[str, Any]:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from docqa_tpu.config import StoreConfig
    from docqa_tpu.engines.encoder import EncoderEngine
    from docqa_tpu.engines.retrieve import (
        FusedRetriever,
        build_fused_search_program,
    )
    from docqa_tpu.index.store import VectorStore

    enc_cfg = _audit_encoder_cfg()
    encoder = EncoderEngine(enc_cfg)
    store = VectorStore(
        StoreConfig(dim=enc_cfg.embed_dim, shard_capacity=64)
    )
    rng = np.random.default_rng(0)
    vecs = rng.standard_normal((8, enc_cfg.embed_dim)).astype(np.float32)
    store.add(vecs, [{"doc_id": f"d{i}"} for i in range(len(vecs))])

    retriever = FusedRetriever(encoder, store)
    retriever.search_texts(["alpha beta"], k=3)
    warm = sum(jit_cache_size(fn) for fn in retriever._fns.values())
    retriever.search_texts(["gamma delta"], k=3)
    after = sum(jit_cache_size(fn) for fn in retriever._fns.values())

    # canonical-program memory at controlled shapes (the same program the
    # shard audit lowers, single-shard here)
    program = jax.jit(build_fused_search_program(
        enc_cfg, None, k=3, masked=False
    ))
    batch, capacity = 1, 64
    mem = lowered_memory(
        program,
        encoder.params,
        jax.ShapeDtypeStruct((batch, enc_cfg.max_seq_len), jnp.int32),
        jax.ShapeDtypeStruct((batch,), jnp.int32),
        jax.ShapeDtypeStruct(
            (capacity, enc_cfg.embed_dim),
            jnp.dtype(store.cfg.dtype),
        ),
        jax.ShapeDtypeStruct((), jnp.int32),
    )
    return {
        "meta": {"programs": len(retriever._fns)},
        "roots": {
            "retrieve_fused": {
                "compiles": warm,
                "expected_shapes": 1,
                "steady_state_retraces": after - warm,
                "memory": mem,
                "peak_bytes": (mem or {}).get("peak_bytes", 0),
                "flops": (mem or {}).get("flops", 0),
                "bytes_accessed": (mem or {}).get("bytes_accessed", 0),
            }
        },
    }


def _audit_seq2seq() -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from docqa_tpu.config import Seq2SeqConfig
    from docqa_tpu.engines.seq2seq import Seq2SeqEngine

    engine = Seq2SeqEngine(Seq2SeqConfig())
    engine.generate_ids([[5, 9, 11]], max_new_tokens=4)
    warm = sum(jit_cache_size(fn) for fn in engine._fns.values())
    engine.generate_ids([[5, 9, 11]], max_new_tokens=4)
    after = sum(jit_cache_size(fn) for fn in engine._fns.values())
    fn = engine._get_fn(4)
    mem = lowered_memory(
        fn,
        engine.params,
        src_ids=jax.ShapeDtypeStruct((1, 64), jnp.int32),
        src_lengths=jax.ShapeDtypeStruct((1,), jnp.int32),
    )
    return {
        "meta": {"programs": len(engine._fns)},
        "roots": {
            "seq2seq_summarize": {
                "compiles": warm,
                "expected_shapes": 1,
                "steady_state_retraces": after - warm,
                "memory": mem,
                "peak_bytes": (mem or {}).get("peak_bytes", 0),
                "flops": (mem or {}).get("flops", 0),
                "bytes_accessed": (mem or {}).get("bytes_accessed", 0),
            }
        },
    }


def _audit_encoder() -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from docqa_tpu.engines.encoder import EncoderEngine

    enc_cfg = _audit_encoder_cfg()
    engine = EncoderEngine(enc_cfg)
    engine.encode_texts(["alpha beta"])
    warm = jit_cache_size(engine._encode)
    engine.encode_texts(["gamma delta"])
    after = jit_cache_size(engine._encode)
    mem = lowered_memory(
        engine._encode,
        params=engine.params,
        ids=jax.ShapeDtypeStruct((8, enc_cfg.max_seq_len), jnp.int32),
        lengths=jax.ShapeDtypeStruct((8,), jnp.int32),
    )
    return {
        "meta": {},
        "roots": {
            "encoder_encode": {
                "compiles": warm,
                "expected_shapes": 1,
                "steady_state_retraces": after - warm,
                "memory": mem,
                "peak_bytes": (mem or {}).get("peak_bytes", 0),
                "flops": (mem or {}).get("flops", 0),
                "bytes_accessed": (mem or {}).get("bytes_accessed", 0),
            }
        },
    }


_AUDITS = {
    "serve": _audit_serve,
    "generate": _audit_generate,
    "retrieve_fused": _audit_retrieve,
    "seq2seq": _audit_seq2seq,
    "encoder": _audit_encoder,
}


# ---------------------------------------------------------------------------
# run + compare
# ---------------------------------------------------------------------------


def run_audit(
    workloads: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Drive every workload; returns the report (the CI artifact)."""
    from docqa_tpu.analysis.shard_audit import enumerate_jit_roots

    names = list(workloads or WORKLOADS)
    report: Dict[str, Any] = {"workloads": {}}
    for name in names:
        report["workloads"][name] = _AUDITS[name]()
    report["jit_roots"] = {"discovered": enumerate_jit_roots()}
    return report


def _iter_roots(section: Dict[str, Any]):
    for wname, wl in section.get("workloads", {}).items():
        for rname, root in wl.get("roots", {}).items():
            yield wname, rname, root


def semantic_violations(report: Dict[str, Any]) -> List[str]:
    """Invariants checked against the MEASUREMENT, so regenerating the
    budget from a broken run still fails the gate."""
    out: List[str] = []
    for wname, rname, root in _iter_roots(report):
        retraces = root.get("steady_state_retraces")
        if retraces != 0:
            out.append(
                f"{wname}/{rname}: {retraces} steady-state retrace(s) — "
                "every admitted shape must be compiled at warmup, never "
                "inside a serving round"
            )
        expected = root.get("expected_shapes")
        if expected is not None and root.get("compiles") != expected:
            out.append(
                f"{wname}/{rname}: {root.get('compiles')} compiled "
                f"specialization(s) for {expected} admitted shape(s) — "
                "the warmed shape set drifted from the admission policy"
            )
        if not root.get("peak_bytes"):
            out.append(
                f"{wname}/{rname}: no memory_analysis measurement — the "
                "HBM gate cannot be satisfied by an empty measurement"
            )
    serve = report.get("workloads", {}).get("serve", {})
    prefill = serve.get("roots", {}).get("serve_prefill", {})
    shapes = prefill.get("per_shape") or {}
    trickle = (shapes.get("trickle") or {}).get("peak_bytes")
    full = (shapes.get("full") or {}).get("peak_bytes")
    if trickle is not None and full is not None and trickle >= full:
        out.append(
            f"serve_prefill: trickle-shape peak ({trickle}B) is not "
            f"smaller than the full-width peak ({full}B) — the narrow "
            "admission shape exists to make trickle rounds cheaper; this "
            "layout broke that"
        )
    meta = serve.get("meta", {})
    if meta.get("paged"):
        # the paged tentpole's headline contract, extended by
        # docqa-prefix: the whole batcher compile matrix is bounded by
        # the ragged token budgets — one COLD program per budget, one
        # WARM (prefix-gather) program per budget when the prefix cache
        # is on, plus the one decode chunk.  Re-derived from the
        # MEASUREMENT so a budget regeneration cannot launder a matrix
        # regrowth toward the per-bucket shape families.
        n_buckets = max(len(meta.get("token_buckets") or ()), 1)
        families = 2 if meta.get("prefix_cache") else 1
        allowed = families * n_buckets + 1
        total = sum(
            int(root.get("compiles") or 0)
            for root in serve.get("roots", {}).values()
        )
        if total > allowed:
            out.append(
                f"serve: {total} compiled programs across prefill+decode "
                f"— the paged batcher's whole matrix must stay <= "
                f"{allowed} ({families} prefill family(ies) x "
                f"{n_buckets} token budget(s) + one decode chunk); a "
                "regrowth toward the per-bucket shape families is a "
                "regression"
            )
    return out


def compare_budget(
    report: Dict[str, Any], budget: Dict[str, Any]
) -> List[str]:
    """Budget-gate violations: semantic invariants on the measurement,
    exact compile counts, per-root HBM ceilings (with TODO growth notes
    rejected), and the jit-root ledger in exact sync."""
    out: List[str] = list(semantic_violations(report))
    want = {
        (w, r): root for w, r, root in _iter_roots(budget)
    }
    got = {
        (w, r): root for w, r, root in _iter_roots(report)
    }
    for key in sorted(set(want) | set(got)):
        wname, rname = key
        if key not in got:
            out.append(
                f"budget root '{wname}/{rname}' was not audited (stale?)"
            )
            continue
        if key not in want:
            out.append(f"root '{wname}/{rname}' has no budget entry")
            continue
        g, w = got[key], want[key]
        if g.get("compiles") != w.get("compiles"):
            out.append(
                f"{wname}/{rname}: {g.get('compiles')} compile(s) "
                f"(budget grants exactly {w.get('compiles')})"
            )
        ceiling = w.get("peak_bytes_ceiling")
        if ceiling is None:
            out.append(
                f"{wname}/{rname}: budget entry lacks peak_bytes_ceiling"
            )
        elif g.get("peak_bytes", 0) > ceiling:
            peak = g.get("peak_bytes", 0)
            pct = 100.0 * (peak - ceiling) / max(ceiling, 1)
            out.append(
                f"{wname}/{rname}: peak {peak}B exceeds the HBM ceiling "
                f"{ceiling}B (+{pct:.0f}%) — justify and regrow the "
                "ceiling via --write-budget + an edited ceiling_note, or "
                "fix the regression"
            )
        note = str(w.get("ceiling_note", ""))
        if "TODO" in note:
            out.append(
                f"{wname}/{rname}: ceiling_note is an unjustified TODO — "
                "a grown ceiling needs a human-written reason"
            )

    ledger = budget.get("jit_roots", {})
    discovered = report.get("jit_roots", {}).get("discovered", [])
    for symbol in discovered:
        reason = ledger.get(symbol)
        if reason is None:
            out.append(
                f"new jit root '{symbol}' is neither covered by a "
                "compile-audit workload nor waived in compile_budget.json"
            )
        elif not str(reason).strip() or "TODO" in str(reason):
            out.append(
                f"jit root '{symbol}' has no real coverage/waiver reason"
            )
    for symbol in sorted(set(ledger) - set(discovered)):
        out.append(
            f"stale jit-root ledger entry '{symbol}' (root no longer "
            "exists)"
        )
    return out


def load_budget(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or default_budget_path()
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def write_budget(
    report: Dict[str, Any], path: Optional[str] = None
) -> Dict[str, Any]:
    """Regenerate the budget from a report.  Compile counts are copied
    (the semantic gate separately forbids steady-state retraces), HBM
    ceilings are PRESERVED while the measurement still fits and only grow
    through a TODO note the gate rejects until a human edits it, and
    jit-root reasons are preserved (new roots get a TODO)."""
    path = path or default_budget_path()
    old: Dict[str, Any] = {}
    if os.path.exists(path):
        old = load_budget(path)
    old_roots = {(w, r): root for w, r, root in _iter_roots(old)}
    old_ledger = old.get("jit_roots", {})

    workloads: Dict[str, Any] = {}
    for wname, wl in report.get("workloads", {}).items():
        roots_out = {}
        for rname, root in wl.get("roots", {}).items():
            peak = int(root.get("peak_bytes", 0))
            prior = old_roots.get((wname, rname), {})
            prior_ceiling = prior.get("peak_bytes_ceiling")
            if prior_ceiling is not None and peak <= prior_ceiling:
                ceiling = prior_ceiling
                note = prior.get("ceiling_note", "")
            else:
                ceiling = int(math.ceil(peak * CEILING_HEADROOM))
                if prior_ceiling is None:
                    note = prior.get(
                        "ceiling_note",
                        "TODO: justify the initial ceiling",
                    )
                else:
                    note = (
                        f"TODO: justify growth from {prior_ceiling} to "
                        f"{ceiling} bytes"
                    )
            roots_out[rname] = {
                "compiles": root.get("compiles"),
                "steady_state_retraces": 0,
                "peak_bytes_ceiling": ceiling,
                "ceiling_note": note,
            }
        workloads[wname] = {
            "meta": wl.get("meta", {}),
            "roots": roots_out,
        }

    budget = {
        "_comment": (
            "Compile-count + HBM budget for the serving jit roots "
            "(docs/STATIC_ANALYSIS.md).  Counts and memory_analysis "
            "bytes are measured by scripts/compile_audit.py; amend ONLY "
            "via --write-budget plus a reviewed ceiling_note for any "
            "grown ceiling.  jit_roots maps every traced root to the "
            "workload covering it or a waiver reason."
        ),
        "workloads": workloads,
        "jit_roots": {
            symbol: old_ledger.get(symbol, "TODO: justify")
            for symbol in report.get("jit_roots", {}).get("discovered", [])
        },
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(budget, f, indent=2, sort_keys=True)
        f.write("\n")
    return budget
