"""resource-flow: every acquired resource reaches exactly one release.

The system's hardest invariants are ledgers — zero leaked KV blocks,
pins released exactly once, every cost record retired — and chaos only
re-proves them under the load it samples.  This checker proves the
*local* half statically: a declared protocol table names the
acquire/release pairs (``BlockAllocator.new_table``→``release``,
cost-ledger ``open``→``retire``, ``spine_submit``→``result``/``cancel``,
trace ``from_headers``→``finish``/``complete``), and an abstract
interpreter walks every control-flow path of each function — early
returns, raise edges, ``finally``, loop ``break``/``continue`` —
holding each locally-acquired resource to exactly one release.

Ownership is local-or-transferred: a resource variable that ESCAPES
(stored into an attribute/container, returned, passed to a call that
isn't a declared borrow) transfers its obligation to the new owner and
tracking ends — cross-function custody is the dynamic ledger witness's
half (``analysis/ledger_audit.py``), mirroring how race_witness splits
the lock-order proof with lock-discipline.  Release APIs here RAISE on
double-free (``BlockAllocator.release``), so a second release on any
path is a finding, not a no-op.

Exception edges are modeled for RAISE-PRONE statements only: explicit
``raise``, calls whose tail is a known raising primitive (``ensure``/
``grow``/``share``/``acquire``/``check``/``perturb``/``result``/
``submit*``/``insert``), and calls resolving (via the chassis'
``resolve_call``) to a package function whose own body raises.  A
``try`` routes the raise edge through its handlers (a handler is
assumed to match — selectivity modeling would trade real leak findings
for type inference the chassis deliberately doesn't do), and
``finally`` bodies run on every exit edge.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
)

# resource statuses
_HELD = 0
_RELEASED = 1
_ESCAPED = 2

State = FrozenSet[Tuple[str, int]]


@dataclasses.dataclass(frozen=True)
class Protocol:
    """One acquire/release pairing."""

    name: str
    # (receiver-substring hint, attr): `hint` matches case-insensitively
    # against the dotted receiver text ("" matches bare calls too)
    acquires: Tuple[Tuple[str, str], ...]
    release_methods: FrozenSet[str]  # x.release() style
    release_funcs: FrozenSet[str]  # retire(x) style (x bare in args)
    borrow_attrs: FrozenSet[str]  # f(.., x, ..) that does NOT take custody


PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol(
        name="kv-table",
        acquires=(("alloc", "new_table"),),
        release_methods=frozenset({"release"}),
        release_funcs=frozenset(),
        # prefix-cache ops map blocks in/out of a table the caller
        # still owns; ensure/grow mutate it in place
        borrow_attrs=frozenset({"acquire", "insert", "share"}),
    ),
    Protocol(
        name="cost-record",
        acquires=(("ledger", "open"),),
        release_methods=frozenset(),
        release_funcs=frozenset({"retire"}),
        borrow_attrs=frozenset({"record_shed"}),
    ),
    Protocol(
        name="spine-ticket",
        acquires=(("", "spine_submit"), ("spine", "submit")),
        release_methods=frozenset({"result", "cancel"}),
        release_funcs=frozenset(),
        borrow_attrs=frozenset(),
    ),
    Protocol(
        name="trace",
        acquires=(("", "from_headers"), ("recorder", "start")),
        release_methods=frozenset({"finish"}),
        release_funcs=frozenset({"finish", "complete", "finish_id"}),
        borrow_attrs=frozenset({"record_span", "add_event", "flag"}),
    ),
)

# call tails that raise as part of their contract, independent of
# whether the chassis can resolve them (deadline.check, faults.perturb,
# allocator growth, spine/batcher admission)
_RAISE_PRONE_TAILS = frozenset(
    {
        "ensure", "grow", "share", "acquire", "check", "perturb",
        "result", "insert", "submit", "submit_request", "submit_ids",
        "submit_text",
    }
)


def _edges() -> Dict[str, Set[State]]:
    return {
        "fall": set(), "return": set(), "raise": set(),
        "break": set(), "continue": set(),
    }


def _merge(into: Dict[str, Set[State]], frm: Dict[str, Set[State]],
           skip: Tuple[str, ...] = ()) -> None:
    for k, v in frm.items():
        if k not in skip:
            into[k] |= v


def _set_var(state: State, var: str, status: int) -> State:
    return frozenset(
        {(v, s) for v, s in state if v != var} | {(var, status)}
    )


def _get_var(state: State, var: str) -> Optional[int]:
    for v, s in state:
        if v == var:
            return s
    return None


class _FnAnalysis:
    """Abstract interpretation of one function body."""

    def __init__(
        self, checker: "ResourceFlowChecker", package: Package,
        fn: FunctionInfo,
    ):
        self.checker = checker
        self.package = package
        self.fn = fn
        # var -> (protocol, acquire lineno) for message/anchor purposes
        self.acquired_at: Dict[str, Tuple[Protocol, int]] = {}
        self.findings: List[Finding] = []
        self._reported: Set[Tuple[str, str]] = set()

    # -- findings -------------------------------------------------------------

    def _report(self, kind: str, var: str, line: int, message: str):
        if (kind, var) in self._reported:
            return
        self._reported.add((kind, var))
        self.findings.append(
            Finding(
                "resource-flow",
                self.fn.module.relpath,
                line,
                self.fn.qualname,
                message,
            )
        )

    def _leak(self, states: Set[State], exit_kind: str) -> None:
        for state in states:
            for var, status in state:
                if status != _HELD:
                    continue
                proto, line = self.acquired_at.get(var, (None, 0))
                pname = proto.name if proto else "resource"
                if exit_kind == "raise":
                    self._report(
                        "leak-raise", var, line,
                        f"{pname} held by '{var}' leaks on an exception "
                        "path — release it in a finally/except or escape "
                        "it before the raising call",
                    )
                else:
                    self._report(
                        "leak", var, line,
                        f"{pname} held by '{var}' is not released on "
                        "every path (leaked on a normal exit)",
                    )

    # -- expression scanning --------------------------------------------------

    def _protocol_for_acquire(self, call: ast.Call) -> Optional[Protocol]:
        name = call_name(call)
        if not name:
            return None
        tail = name.rsplit(".", 1)[-1]
        receiver = name[: -(len(tail) + 1)] if "." in name else ""
        for proto in PROTOCOLS:
            for hint, attr in proto.acquires:
                if tail != attr:
                    continue
                if hint and hint not in receiver.lower():
                    continue
                return proto
        return None

    def _call_is_raise_prone(self, call: ast.Call) -> bool:
        name = call_name(call)
        tail = name.rsplit(".", 1)[-1] if name else ""
        if tail in _RAISE_PRONE_TAILS:
            return True
        callee = self.package.resolve_call(self.fn, call)
        return callee is not None and self.checker.has_raise(callee)

    def _scan_expr(
        self, node: ast.AST, states: Set[State]
    ) -> Tuple[Set[State], bool]:
        """Apply release/borrow/escape effects of one expression tree in
        source order; returns (new states, may_raise)."""
        may_raise = False
        tracked = set(self.acquired_at)

        def tracked_name(n: ast.AST) -> Optional[str]:
            if isinstance(n, ast.Name) and n.id in tracked:
                return n.id
            return None

        def apply(op: str, var: str, line: int) -> None:
            nonlocal states
            out: Set[State] = set()
            for state in states:
                status = _get_var(state, var)
                if status is None:
                    out.add(state)
                    continue
                if op == "release":
                    if status == _RELEASED:
                        proto, _ = self.acquired_at[var]
                        self._report(
                            "double", var, line,
                            f"{proto.name} held by '{var}' released "
                            "twice on one path (release raises on "
                            "double-free)",
                        )
                    out.add(_set_var(state, var, _RELEASED))
                elif op == "escape":
                    if status == _HELD:
                        out.add(_set_var(state, var, _ESCAPED))
                    else:
                        out.add(state)
                else:  # borrow
                    out.add(state)
            states = out

        def walk(n: ast.AST) -> None:
            nonlocal may_raise
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                # nested scope: a closure capturing the var keeps it
                # alive beyond this frame's reasoning — escape it
                for inner in ast.walk(n):
                    var = tracked_name(inner)
                    if var:
                        apply("escape", var, n.lineno)
                return
            if isinstance(n, ast.Call):
                # receiver-method form: x.release() / x.result() /
                # x.set_session() — classify by the protocol's tables
                func = n.func
                recv_var = None
                if isinstance(func, ast.Attribute):
                    recv_var = tracked_name(func.value)
                if recv_var is not None:
                    proto, _ = self.acquired_at[recv_var]
                    if func.attr in proto.release_methods:
                        apply("release", recv_var, n.lineno)
                    # any other method on the var is a borrow
                else:
                    walk(func)
                name = call_name(n)
                tail = name.rsplit(".", 1)[-1] if name else ""
                for arg in list(n.args) + [kw.value for kw in n.keywords]:
                    var = tracked_name(arg)
                    if var is not None:
                        proto, _ = self.acquired_at[var]
                        if tail in proto.release_funcs:
                            apply("release", var, n.lineno)
                        elif tail in proto.borrow_attrs:
                            apply("borrow", var, n.lineno)
                        else:
                            apply("escape", var, n.lineno)
                    else:
                        walk(arg)
                if self._call_is_raise_prone(n):
                    may_raise = True
                return
            if isinstance(n, ast.Attribute):
                # attribute READ off the var (table.blocks) — neutral
                if tracked_name(n.value) is not None:
                    return
            if isinstance(n, (ast.Compare, ast.BoolOp)):
                # identity/None tests keep tracking alive
                for child in ast.iter_child_nodes(n):
                    if tracked_name(child) is None and not (
                        isinstance(child, (ast.Name, ast.Constant))
                    ):
                        walk(child)
                return
            var = tracked_name(n)
            if var is not None:
                apply("escape", var, getattr(n, "lineno", 0))
                return
            for child in ast.iter_child_nodes(n):
                walk(child)

        walk(node)
        return states, may_raise

    # -- statement execution --------------------------------------------------

    def exec_block(
        self, stmts: List[ast.stmt], in_states: Set[State]
    ) -> Dict[str, Set[State]]:
        out = _edges()
        cur = set(in_states)
        for stmt in stmts:
            if not cur:
                break
            e = self.exec_stmt(stmt, cur)
            _merge(out, e, skip=("fall",))
            cur = e["fall"]
        out["fall"] = cur
        return out

    def exec_stmt(
        self, stmt: ast.stmt, states: Set[State]
    ) -> Dict[str, Set[State]]:
        out = _edges()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested defs: closure capture escapes (handled in _scan)
            states, _ = self._scan_expr(stmt, states)
            out["fall"] = states
            return out
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                states, _ = self._scan_expr(stmt.value, states)
            out["return"] = states
            return out
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                states, _ = self._scan_expr(stmt.exc, states)
            out["raise"] = states
            return out
        if isinstance(stmt, ast.Break):
            out["break"] = states
            return out
        if isinstance(stmt, ast.Continue):
            out["continue"] = states
            return out
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return self._exec_assign(stmt, states)
        if isinstance(stmt, ast.Expr):
            new_states, may_raise = self._scan_expr(stmt.value, states)
            if may_raise:
                out["raise"] |= new_states
            out["fall"] = new_states
            return out
        if isinstance(stmt, ast.If):
            t, _ = self._scan_expr(stmt.test, states)
            _merge(out, self.exec_block(stmt.body, t))
            _merge(out, self.exec_block(stmt.orelse, t))
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._exec_loop(stmt, states)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, states)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                states, may_raise = self._scan_expr(
                    item.context_expr, states
                )
                if may_raise:
                    out["raise"] |= states
            _merge(out, self.exec_block(stmt.body, states))
            return out
        # generic statement (assert, delete, global, import, pass, …):
        # scan child expressions for effects, no control flow
        may_raise = False
        for child in ast.iter_child_nodes(stmt):
            states, mr = self._scan_expr(child, states)
            may_raise = may_raise or mr
        if may_raise:
            out["raise"] |= states
        out["fall"] = states
        return out

    def _exec_assign(
        self, stmt: ast.stmt, states: Set[State]
    ) -> Dict[str, Set[State]]:
        out = _edges()
        value = getattr(stmt, "value", None)
        targets = (
            stmt.targets
            if isinstance(stmt, ast.Assign)
            else [stmt.target]
        )
        proto = (
            self._protocol_for_acquire(value)
            if isinstance(value, ast.Call)
            else None
        )
        if (
            proto is not None
            and len(targets) == 1
            and isinstance(targets[0], ast.Name)
        ):
            var = targets[0].id
            # the acquire call's ARGUMENTS may still release/escape
            # other tracked vars and may raise (pre-acquire)
            pre, may_raise = self._scan_expr_call_args(value, states)
            if may_raise:
                out["raise"] |= pre
            new: Set[State] = set()
            for state in pre:
                if _get_var(state, var) == _HELD:
                    old_proto, old_line = self.acquired_at[var]
                    self._report(
                        "rebind", var, stmt.lineno,
                        f"'{var}' rebound while still holding an "
                        f"unreleased {old_proto.name} (acquired at "
                        f"line {old_line})",
                    )
                new.add(_set_var(state, var, _HELD))
            self.acquired_at[var] = (proto, stmt.lineno)
            out["fall"] = new
            return out
        if value is not None:
            states, may_raise = self._scan_expr(value, states)
            if may_raise:
                out["raise"] |= states
        # escape through non-Name targets / aliasing
        for t in targets:
            if isinstance(t, ast.Name):
                # plain alias y = x already escaped x in the value scan
                continue
            states, _ = self._scan_expr(t, states)
        out["fall"] = states
        return out

    def _scan_expr_call_args(
        self, call: ast.Call, states: Set[State]
    ) -> Tuple[Set[State], bool]:
        may_raise = self._call_is_raise_prone(call)
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            states, mr = self._scan_expr(arg, states)
            may_raise = may_raise or mr
        return states, may_raise

    def _exec_loop(
        self, stmt, states: Set[State]
    ) -> Dict[str, Set[State]]:
        out = _edges()
        if isinstance(stmt, ast.While):
            states, _ = self._scan_expr(stmt.test, states)
        else:
            states, _ = self._scan_expr(stmt.iter, states)
            states, _ = self._scan_expr(stmt.target, states)
        seen: Set[State] = set(states)
        frontier = set(states)
        falls: Set[State] = set(states)  # zero-iteration exit
        for _ in range(10):
            if not frontier:
                break
            e = self.exec_block(stmt.body, frontier)
            _merge(out, e, skip=("fall", "break", "continue"))
            falls |= e["break"] | e["fall"]
            nxt = (e["fall"] | e["continue"]) - seen
            seen |= nxt
            frontier = nxt
        _merge(out, self.exec_block(stmt.orelse, falls), skip=())
        out["fall"] |= falls
        return out

    def _exec_try(
        self, stmt: ast.Try, states: Set[State]
    ) -> Dict[str, Set[State]]:
        out = _edges()
        body = self.exec_block(stmt.body, states)
        raised = body["raise"]
        pre_final = _edges()
        for k in ("return", "break", "continue"):
            pre_final[k] |= body[k]
        if stmt.handlers:
            for handler in stmt.handlers:
                h = self.exec_block(handler.body, raised)
                _merge(pre_final, h)
        else:
            pre_final["raise"] |= raised
        orelse = self.exec_block(stmt.orelse, body["fall"])
        _merge(pre_final, orelse)
        if not stmt.finalbody:
            return pre_final
        for kind, sts in pre_final.items():
            if not sts:
                continue
            f = self.exec_block(stmt.finalbody, sts)
            out[kind] |= f["fall"]
            _merge(out, f, skip=("fall",))
        return out

    # -- entry ---------------------------------------------------------------

    def run(self) -> List[Finding]:
        body = list(self.fn.node.body)  # type: ignore[attr-defined]
        edges = self.exec_block(body, {frozenset()})
        self._leak(edges["fall"] | edges["return"], "normal")
        self._leak(edges["raise"], "raise")
        return self.findings


class ResourceFlowChecker:
    rule = "resource-flow"

    def __init__(self) -> None:
        self._has_raise: Dict[int, bool] = {}

    def has_raise(self, fn: FunctionInfo) -> bool:
        cached = self._has_raise.get(id(fn))
        if cached is None:
            cached = any(
                isinstance(n, ast.Raise)
                for n in ast.walk(fn.node)
            )
            self._has_raise[id(fn)] = cached
        return cached

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.functions:
            if not self._worth_analyzing(fn):
                continue
            out.extend(_FnAnalysis(self, package, fn).run())
        return out

    @staticmethod
    def _worth_analyzing(fn: FunctionInfo) -> bool:
        """Cheap prescan: only run the interpreter over functions whose
        own body contains an acquire-shaped call."""
        acquire_attrs = {
            attr for proto in PROTOCOLS for _hint, attr in proto.acquires
        }
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                name = call_name(node)
                tail = name.rsplit(".", 1)[-1] if name else ""
                if tail in acquire_attrs:
                    return True
        return False


def static_sites(package: Package) -> Dict[str, List[Dict]]:
    """Every acquire/release call site per protocol, keyed for the
    dynamic ledger witness: the witness maps runtime events back onto
    exactly these ``path:lineno`` ids and fails on any witnessed site
    the static table doesn't know (witnessed ⊆ static)."""
    sites: Dict[str, List[Dict]] = {p.name: [] for p in PROTOCOLS}
    for fn in package.functions:
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            tail = name.rsplit(".", 1)[-1]
            receiver = (
                name[: -(len(tail) + 1)] if "." in name else ""
            )
            for proto in PROTOCOLS:
                kinds = []
                for hint, attr in proto.acquires:
                    if tail == attr and (
                        not hint or hint in receiver.lower()
                    ):
                        kinds.append("acquire")
                        break
                if (
                    tail in proto.release_methods
                    or tail in proto.release_funcs
                ):
                    kinds.append("release")
                for kind in kinds:
                    sites[proto.name].append(
                        {
                            "kind": kind,
                            "path": fn.module.path,
                            "relpath": fn.module.relpath,
                            "line": node.lineno,
                            "symbol": fn.qualname,
                        }
                    )
    return sites
