"""Shared entropy-source classification for docqa-detcheck.

Every determinism gate in this repo is a *replay* gate: two runs under
the same seeds must produce bitwise-identical token streams, retrieval
ids, and journal states.  The enemy is entropy — values a process mints
that the next process (or the same process restarted) cannot re-mint.
This module is the one place that knows what counts as an entropy
source; the four detcheck rules and the replay-witness manifest
(``determinism_manifest.json``) all classify through it so the static
rules, the dynamic gate, and the ledger can never disagree about what
"entropy" means.

Kinds:

* ``rng`` — explicit RNG mints: ``jax.random.PRNGKey``/``key``,
  ``np.random.default_rng``, ``random.Random``, seeding calls.  Sanctioned
  when the seed derives from config/request state (the manifest entry
  records the derivation);
* ``process`` — per-process entropy that can NEVER replay: ``os.urandom``,
  ``secrets.*``, ``uuid.uuid1``/``uuid4``.  Sanctioned only when the value
  is minted once and *persisted* (replay reads it back, never re-mints) or
  is deliberately process-local (the PHI unlinkability salt);
* ``wallclock`` — ``time.time``/``time_ns``, ``datetime.now``/``utcnow``:
  identity-capable clocks (two runs read different values).  Sanctioned for
  telemetry timestamps and scheduling fields, never for keys.

Monotonic *interval* clocks (``perf_counter``, ``monotonic``) are
deliberately NOT enumerated into the manifest: an interval clock measures
durations and cannot mint identity, and the tree reads one in nearly every
module — ledgering ~100 sites would bury the entries that matter.  The
entropy-in-state rule still polices them at key sinks (a perf_counter
value concatenated into a cache key is exactly as unreplayable as
``time.time``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from docqa_tpu.analysis.core import (
    Module,
    Package,
    call_name,
    stmt_walk,
)

# resolved dotted call -> entropy kind (exact matches)
RNG_MINTS = frozenset(
    {
        "jax.random.PRNGKey",
        "jax.random.key",
        "numpy.random.default_rng",
        "numpy.random.RandomState",
        "numpy.random.SeedSequence",
        "numpy.random.seed",
        "random.Random",
        "random.seed",
    }
)
PROCESS_SOURCES = frozenset(
    {
        "os.urandom",
        "uuid.uuid1",
        "uuid.uuid4",
    }
)
WALLCLOCK_SOURCES = frozenset(
    {
        "time.time",
        "time.time_ns",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
        "datetime.date.today",
    }
)
# interval clocks: policed at sinks by entropy-in-state, excluded from
# the manifest enumeration (see module docstring)
MONOTONIC_CLOCKS = frozenset(
    {
        "time.perf_counter",
        "time.perf_counter_ns",
        "time.monotonic",
        "time.monotonic_ns",
    }
)


def classify_entropy_call(
    module: Module, node: ast.Call
) -> Optional[Tuple[str, str]]:
    """(kind, resolved-dotted-name) for an entropy-minting call, else
    None.  Resolution goes through the module's import-alias map, so
    ``jrandom.PRNGKey`` and ``from time import time`` both classify."""
    name = call_name(node)
    if not name:
        return None
    resolved = module.resolve_alias(name)
    if resolved in RNG_MINTS:
        return ("rng", resolved)
    if resolved in PROCESS_SOURCES or resolved.startswith("secrets."):
        return ("process", resolved)
    if resolved in WALLCLOCK_SOURCES:
        return ("wallclock", resolved)
    return None


def enumerate_entropy_sites(package: Package) -> List[Dict[str, str]]:
    """Every sanctioned-or-not entropy mint in the package, one entry per
    (kind, path, symbol, call) — the unit the determinism manifest
    ledgers.  Multiple same-call sites inside one function collapse to
    one entry (the justification covers the function's scheme, not each
    textual occurrence), so line drift never churns the manifest."""
    seen = {}
    for fn in package.functions:
        for node in stmt_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            hit = classify_entropy_call(fn.module, node)
            if hit is None:
                continue
            kind, call = hit
            key = (kind, fn.module.relpath, fn.qualname, call)
            seen.setdefault(key, getattr(node, "lineno", 1))
    for module in package.modules:
        for node in stmt_walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = classify_entropy_call(module, node)
            if hit is None:
                continue
            kind, call = hit
            key = (kind, module.relpath, "<module>", call)
            seen.setdefault(key, getattr(node, "lineno", 1))
    out = [
        {
            "kind": kind,
            "path": path,
            "symbol": symbol,
            "call": call,
            "line": line,
        }
        for (kind, path, symbol, call), line in seen.items()
    ]
    out.sort(key=lambda e: (e["path"], e["symbol"], e["call"]))
    return out
