"""rng-discipline: jax.random keys are affine on the serving path.

JAX's functional RNG makes determinism *checkable*: a key is an explicit
value, and the contract is affine use — consume a key at most once (a
sampling call, or handing it to a callee), and derive every further key
with ``split``/``fold_in``.  Reusing a consumed key silently correlates
draws that must be independent; a *fixed* ``PRNGKey(<literal>)`` on the
request path makes every request sample identically — both break the
bitwise replay gates (warm==cold, spec on==off) in ways no test that
only runs one process can see.

Scope: the /ask chain (``deadline_flow.REQUEST_PATH_MODULES``) plus the
decode/batching engines and the broker (whose redelivery jitter must
come from seeded state); fixtures opt in with
the ``docqa-lint: request-path`` pragma.

Findings:

1. ``jax.random.PRNGKey(<numeric literal>)`` / ``jax.random.key(<lit>)``
   — a fixed key reachable from the request path.  Per-request keys must
   derive from the counter-minted scheme (``serve._next_rng`` /
   ``GenerateEngine.next_request_key``: ``PRNGKey(seed * 100_003 +
   counter)``).  Structural exemptions, not baselines: a literal key
   inside ``.lower(...)`` arguments (an AOT shape probe traces shapes,
   never draws), and the body of ``greedy_dummy_key`` (the one declared
   constructor for keys that greedy paths thread but never consume).
2. Key reuse: a tracked key name (minted by ``PRNGKey``/``key``/
   ``split``/``fold_in``/the counter scheme, or a parameter named
   ``rng``/``key``/``rng_key``/``prng_key``) passed to a second call
   without an intervening rebind from a derive.  Loop bodies are scanned
   twice so a consume-without-rebind inside a loop flags; ``if``/``else``
   branches merge conservatively (consumed in either arm counts).
3. Module-level RNG (``np.random.<fn>`` bar ``default_rng``-family,
   bare ``random.<fn>`` bar ``random.Random``) — global mutable RNG
   state in device-result or replay-key paths; use a seeded generator
   instance or the engine key scheme.

Resolution is name-based (the chassis has no type system): only bare
names are tracked (``self._rng`` attributes escape), and a tracked name
returned or stored escapes tracking rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Module,
    Package,
    call_name,
)
from docqa_tpu.analysis.deadline_flow import REQUEST_PATH_MODULES

RNG_SCOPE_MODULES = REQUEST_PATH_MODULES | frozenset(
    {
        "docqa_tpu.engines.generate",
        "docqa_tpu.engines.paged",
        "docqa_tpu.engines.qos",
        "docqa_tpu.engines.seq2seq",
        "docqa_tpu.service.broker",
    }
)

# The declared constructor for keys greedy paths thread but never
# consume (temperature==0.0 takes the argmax branch; the sampling key is
# dead).  The checker exempts its BODY structurally — callers get a
# dummy key without owning a literal-key site.
GREEDY_DUMMY_KEY = "greedy_dummy_key"

_KEY_MINTS = frozenset({"jax.random.PRNGKey", "jax.random.key"})
_KEY_DERIVES = frozenset({"jax.random.split", "jax.random.fold_in"})
# counter-minted per-request scheme accessors (serve.py / generate.py)
_KEY_SCHEME_TAILS = frozenset(
    {"next_request_key", "_next_rng", GREEDY_DUMMY_KEY}
)
_KEY_PARAMS = frozenset({"rng", "key", "rng_key", "prng_key"})
_NP_RANDOM_OK = frozenset(
    {"default_rng", "Generator", "RandomState", "SeedSequence"}
)


def _is_numeric_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_numeric_literal(node.operand)
    return False


class RngDisciplineChecker:
    rule = "rng-discipline"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.functions:
            module = fn.module
            if not (
                module.name in RNG_SCOPE_MODULES
                or module.request_path_pragma
            ):
                continue
            self._scan(fn, out)
        for module in package.modules:
            if not (
                module.name in RNG_SCOPE_MODULES
                or module.request_path_pragma
            ):
                continue
            self._scan_module_level(module, out)
        return out

    # -- shared call checks ---------------------------------------------------

    def _resolved(self, module: Module, node: ast.Call) -> str:
        name = call_name(node)
        return module.resolve_alias(name) if name else ""

    def _check_literal_key(
        self,
        module: Module,
        node: ast.Call,
        symbol: str,
        exempt: Set[int],
        out: List[Finding],
    ) -> None:
        if id(node) in exempt:
            return
        if self._resolved(module, node) not in _KEY_MINTS:
            return
        if len(node.args) == 1 and _is_numeric_literal(node.args[0]):
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    symbol,
                    "fixed jax.random.PRNGKey(<literal>) on the request "
                    "path — every request would sample identically; mint "
                    "per-request keys from the counter scheme "
                    "(GenerateEngine.next_request_key / serve._next_rng), "
                    "or thread greedy_dummy_key() on greedy-only paths",
                )
            )

    def _check_module_rng(
        self,
        module: Module,
        node: ast.Call,
        symbol: str,
        out: List[Finding],
    ) -> None:
        resolved = self._resolved(module, node)
        if not resolved:
            return
        tail = resolved.rsplit(".", 1)[-1]
        if (
            resolved.startswith("numpy.random.")
            and tail not in _NP_RANDOM_OK
        ):
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    symbol,
                    f"np.random.{tail}() — global numpy RNG state on a "
                    "device-result/replay path; use a seeded "
                    "np.random.default_rng instance",
                )
            )
        elif (
            resolved.startswith("random.")
            and resolved.count(".") == 1
            and tail != "Random"
        ):
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    symbol,
                    f"random.{tail}() — process-global RNG on a "
                    "device-result/replay path; use a seeded "
                    "random.Random instance or the engine key scheme",
                )
            )

    def _lower_exempt_ids(self, root: ast.AST) -> Set[int]:
        """ids of every node inside ``.lower(...)`` call arguments — AOT
        shape probes pass placeholder keys that trace shapes and never
        draw."""
        exempt: Set[int] = set()
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name or name.rsplit(".", 1)[-1] != "lower":
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    exempt.add(id(sub))
        return exempt

    # -- module level ---------------------------------------------------------

    def _scan_module_level(self, module: Module, out: List[Finding]) -> None:
        exempt = self._lower_exempt_ids(module.tree)
        stack = list(ast.iter_child_nodes(module.tree))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                self._check_literal_key(
                    module, node, "<module>", exempt, out
                )
                self._check_module_rng(module, node, "<module>", out)
            stack.extend(ast.iter_child_nodes(node))

    # -- per-function affine scan ---------------------------------------------

    def _scan(self, fn: FunctionInfo, out: List[Finding]) -> None:
        module = fn.module
        exempt = self._lower_exempt_ids(fn.node)
        in_dummy = fn.name == GREEDY_DUMMY_KEY
        # Key-named PARAMS are tracked only when the body actually
        # touches jax.random — ``rng``/``key`` params elsewhere are
        # numpy generators or cache-key strings, and flagging a dict key
        # passed to two calls would be pure noise.  Locally minted keys
        # are always tracked.
        touches_jax_random = False
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                resolved = self._resolved(module, node)
                name = call_name(node)
                if resolved.startswith("jax.random.") or (
                    name
                    and name.rsplit(".", 1)[-1] in _KEY_SCHEME_TAILS
                ):
                    touches_jax_random = True
                    break
        # fresh[name]: True = mint/derive result not yet consumed;
        # False = consumed once already
        fresh: Dict[str, bool] = (
            {p: True for p in fn.params if p in _KEY_PARAMS}
            if touches_jax_random
            else {}
        )
        emitted: Set[tuple] = set()

        def add(node, message, dedup_key=None) -> None:
            key = dedup_key or (getattr(node, "lineno", 1), message)
            if key in emitted:
                return
            emitted.add(key)
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    fn.qualname,
                    message,
                )
            )

        def key_source(value: ast.AST) -> Optional[str]:
            """'fresh' when the expression mints/derives a key (or indexes
            one out of a split result), else None."""
            if isinstance(value, ast.Subscript):
                return key_source(value.value)
            if not isinstance(value, ast.Call):
                return None
            resolved = self._resolved(module, value)
            if resolved in _KEY_MINTS or resolved in _KEY_DERIVES:
                return "fresh"
            name = call_name(value)
            if name and name.rsplit(".", 1)[-1] in _KEY_SCHEME_TAILS:
                return "fresh"
            return None

        def consume_args(call: ast.Call) -> None:
            """Any call consumes the tracked key names in its argument
            list — including split/fold_in (they consume the old key and
            mint fresh ones into the assignment targets)."""
            if id(call) in exempt:
                return
            for arg in list(call.args) + [k.value for k in call.keywords]:
                target = arg
                if isinstance(target, ast.Starred):
                    target = target.value
                if not isinstance(target, ast.Name):
                    continue
                name = target.id
                if name not in fresh:
                    continue
                if not fresh[name]:
                    add(
                        call,
                        f"key '{name}' reused after being consumed — "
                        "jax.random keys are affine; split/fold_in "
                        "before every additional use",
                        dedup_key=(getattr(call, "lineno", 1), name),
                    )
                fresh[name] = False

        def handle_expr(node: ast.AST) -> None:
            stack = [node]
            while stack:
                cur = stack.pop()
                if isinstance(
                    cur,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                if isinstance(cur, ast.Call):
                    if not in_dummy:
                        self._check_literal_key(
                            module, cur, fn.qualname, exempt, out
                        )
                    self._check_module_rng(module, cur, fn.qualname, out)
                    consume_args(cur)
                stack.extend(ast.iter_child_nodes(cur))

        def untrack_escapes(node: ast.AST) -> None:
            """A tracked key that escapes (returned, yielded, stored on
            an attribute/container) leaves the affine scan — ownership
            moved somewhere this name-based pass cannot follow."""
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in fresh:
                    del fresh[sub.id]

        def bind_assign(stmt: ast.Assign) -> None:
            src = key_source(stmt.value)
            is_tuple_derive = isinstance(stmt.value, ast.Call) and (
                self._resolved(module, stmt.value) in _KEY_DERIVES
            )
            for target in stmt.targets:
                names = []
                if isinstance(target, ast.Name):
                    names = [target]
                elif isinstance(target, (ast.Tuple, ast.List)):
                    names = [
                        e for e in target.elts if isinstance(e, ast.Name)
                    ]
                elif isinstance(target, (ast.Attribute, ast.Subscript)):
                    # storing INTO state: the value escapes
                    untrack_escapes(stmt.value)
                    continue
                for n in names:
                    if src == "fresh" or (is_tuple_derive and names):
                        fresh[n.id] = True
                    elif n.id in fresh:
                        del fresh[n.id]

        def merge(base: Dict[str, bool], *branches: Dict[str, bool]):
            names = set()
            for b in branches:
                names |= set(b)
            base.clear()
            for name in names:
                vals = [b[name] for b in branches if name in b]
                if len(vals) == len(branches):
                    base[name] = all(vals)
                # tracked in only one arm: untracked after the join
                # (the other arm escaped/rebound it — don't guess)

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if isinstance(stmt, ast.Assign):
                    handle_expr(stmt.value)
                    bind_assign(stmt)
                    continue
                if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    if stmt.value is not None:
                        handle_expr(stmt.value)
                    continue
                if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
                    getattr(stmt, "value", None), (ast.Yield, ast.YieldFrom)
                ):
                    if stmt.value.value is not None:
                        handle_expr(stmt.value.value)
                        untrack_escapes(stmt.value.value)
                    continue
                if isinstance(stmt, ast.Return):
                    if stmt.value is not None:
                        handle_expr(stmt.value)
                        untrack_escapes(stmt.value)
                    continue
                if isinstance(stmt, ast.If):
                    handle_expr(stmt.test)
                    saved = dict(fresh)
                    walk(stmt.body)
                    then_end = dict(fresh)
                    fresh.clear()
                    fresh.update(saved)
                    walk(stmt.orelse)
                    else_end = dict(fresh)
                    merge(fresh, then_end, else_end)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    handle_expr(stmt.iter)
                    # two passes: a consume-without-rebind shows up when
                    # iteration two replays the body
                    walk(stmt.body)
                    walk(stmt.body)
                    walk(stmt.orelse)
                    continue
                if isinstance(stmt, ast.While):
                    handle_expr(stmt.test)
                    walk(stmt.body)
                    walk(stmt.body)
                    walk(stmt.orelse)
                    continue
                if isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for handler in stmt.handlers:
                        walk(handler.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        handle_expr(item.context_expr)
                    walk(stmt.body)
                    continue
                for _name, field in ast.iter_fields(stmt):
                    if isinstance(field, ast.expr):
                        handle_expr(field)
                    elif isinstance(field, list):
                        if field and isinstance(field[0], ast.stmt):
                            walk(field)
                        elif field and isinstance(field[0], ast.expr):
                            for e in field:
                                handle_expr(e)

        body = getattr(fn.node, "body", None)
        if body:
            walk(body)
