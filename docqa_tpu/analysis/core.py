"""docqa-lint core: package model, suppressions, baseline, runner.

The four checkers (deadline-flow, jit-purity, lock-discipline, phi-taint)
encode invariants PR 1 established by hand — every blocking wait on the
request path clamps to the request :class:`~docqa_tpu.resilience.deadline.
Deadline`, jit-traced code stays pure, lock acquisition keeps one global
order with no blocking I/O inside critical sections, and raw pre-deid text
never reaches logs/metrics/external payloads.  This module holds everything
the checkers share:

* :class:`Package` — a parsed view of the tree: one :class:`Module` per
  file (AST + per-line suppressions + import-alias map) and one
  :class:`FunctionInfo` per ``def`` (qualname, params, enclosing class),
  indexed by bare name so checkers can resolve ``self.engine.foo(...)``
  style calls without a type system;
* suppressions — ``# docqa-lint: disable=<rule>[,<rule>]`` on the
  *finding's* line silences that rule there (``disable=all`` silences every
  rule).  Suppressions are for intentional, locally-justified exceptions;
* :class:`Baseline` — a checked-in JSON ledger of accepted findings, each
  carrying a human justification.  Findings are matched by a stable
  fingerprint (rule + path + enclosing symbol + message — deliberately
  *not* the line number, so unrelated edits don't churn the file).  The
  gate fails on any NEW finding and on any STALE entry (baselined finding
  that no longer fires), keeping the ledger exactly in sync with the tree;
* the :func:`run` entrypoint used by ``scripts/lint.py`` and the
  ``pytest -m lint`` gate.

Checkers are heuristic by design (no type inference): each documents its
resolution rules, and every rule can be silenced per line or per finding.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_SUPPRESS_RE = re.compile(r"#\s*docqa-lint:\s*disable=([\w, -]+)")
_REQUEST_PATH_PRAGMA_RE = re.compile(r"#\s*docqa-lint:\s*request-path")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # package-root-relative posix path
    line: int
    symbol: str  # qualname of the enclosing function, or "<module>"
    message: str

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: everything but the line
        number (line drift from unrelated edits must not churn the
        baseline; a moved-but-unchanged finding still matches)."""
        raw = "|".join((self.rule, self.path, self.symbol, self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message} ({self.symbol})"


# ---------------------------------------------------------------------------
# source model
# ---------------------------------------------------------------------------


def expr_text(node: Optional[ast.AST]) -> str:
    """Best-effort source text of an expression (resolution heuristics
    compare these strings; they never eval anything)."""
    if node is None:
        return ""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed synthetic nodes
        return ""


def call_name(node: ast.Call) -> str:
    """Dotted text of a call target: ``self.registry.set_status``,
    ``time.sleep``, ``print`` ...  Empty for computed targets."""
    return _dotted(node.func)


def dotted_name(node: ast.AST) -> str:
    """Dotted text of a Name/Attribute chain ("self.registry.get")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


_dotted = dotted_name  # internal alias


def stmt_walk(root: ast.AST):
    """Walk a function body WITHOUT descending into nested defs/lambdas
    (they have their own scopes; checkers visit them separately)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Module:
    """One parsed source file."""

    def __init__(self, path: str, relpath: str, source: str, name: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.name = name  # dotted module name
        self.tree = ast.parse(source, filename=path)
        # per-line suppressions: line -> set of rule names (or {"all"})
        self.suppressed: Dict[int, Set[str]] = {}
        for i, line in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                if rules:
                    self.suppressed[i] = rules
        self.request_path_pragma = bool(
            _REQUEST_PATH_PRAGMA_RE.search(source)
        )
        # local alias -> dotted origin ("np" -> "numpy",
        # "time_monotonic" -> "time.monotonic", "faults" ->
        # "docqa_tpu.resilience.faults")
        self.imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressed.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    def resolve_alias(self, dotted: str) -> str:
        """Rewrite a call/attr chain's first segment through the import
        map: ``_time.sleep`` -> ``time.sleep``."""
        head, _, rest = dotted.partition(".")
        origin = self.imports.get(head)
        if origin is None:
            return dotted
        return f"{origin}.{rest}" if rest else origin


@dataclasses.dataclass
class FunctionInfo:
    """One ``def`` (sync or async), anywhere in a module."""

    module: Module
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str  # "Class.method" / "outer.<locals>.inner" / "func"
    class_name: Optional[str]

    @property
    def name(self) -> str:
        return self.node.name  # type: ignore[attr-defined]

    @property
    def params(self) -> List[str]:
        a = self.node.args  # type: ignore[attr-defined]
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    @property
    def has_kwargs(self) -> bool:
        return self.node.args.kwarg is not None  # type: ignore[attr-defined]


class _FunctionCollector(ast.NodeVisitor):
    def __init__(self, module: Module):
        self.module = module
        self.stack: List[str] = []
        self.class_stack: List[str] = []
        self.out: List[FunctionInfo] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.stack.pop()

    def _visit_fn(self, node) -> None:
        qual = ".".join(self.stack + [node.name])
        self.out.append(
            FunctionInfo(
                module=self.module,
                node=node,
                qualname=qual,
                class_name=self.class_stack[-1] if self.class_stack else None,
            )
        )
        self.stack.append(node.name)
        self.stack.append("<locals>")
        self.generic_visit(node)
        self.stack.pop()
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


# Method/function names too generic for unique-bare-name call resolution:
# ``self.store.add(...)`` must not resolve to an arbitrary package function
# that happens to be called ``add``.
GENERIC_NAMES = frozenset(
    "get set add search check wait result text call run stop start close "
    "read write update append encode decode reset build load save format "
    "items keys values count copy clear pop remove join split strip "
    "submit handler body main "
    # array/statistics method names (jnp/np tracer methods must never
    # resolve to a same-named package function)
    "mean std var max min sum all any round sort take clip dot "
    "reshape astype ravel flatten squeeze transpose argmax argmin "
    "argsort cumsum prod repeat tile observe".split()
)


class Package:
    """Parsed view of every ``*.py`` under a root directory."""

    def __init__(self, modules: List[Module]):
        self.modules = modules
        self.functions: List[FunctionInfo] = []
        for m in modules:
            collector = _FunctionCollector(m)
            collector.visit(m.tree)
            self.functions.extend(collector.out)
        self.by_bare_name: Dict[str, List[FunctionInfo]] = {}
        for f in self.functions:
            self.by_bare_name.setdefault(f.name, []).append(f)

    @classmethod
    def load(cls, root: str, package_name: Optional[str] = None) -> "Package":
        root = os.path.abspath(root)
        if os.path.isfile(root):
            base = os.path.dirname(root)
            files = [root]
        else:
            base = root
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [
                    d for d in sorted(dirnames) if d != "__pycache__"
                ]
                files.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        # normalize to the PACKAGE root (outermost dir with __init__.py):
        # fingerprint paths must be identical whether the analyzer was
        # pointed at the package, a subpackage, or a single file —
        # otherwise a path-scoped run mismatches every baseline entry
        while os.path.exists(
            os.path.join(os.path.dirname(base), "__init__.py")
        ) and os.path.dirname(base) != base:
            base = os.path.dirname(base)
        pkg = package_name or os.path.basename(base.rstrip(os.sep))
        modules = []
        for path in files:
            rel = os.path.relpath(path, base)
            dotted = rel[: -len(".py")].replace(os.sep, ".")
            if dotted.endswith(".__init__"):
                dotted = dotted[: -len(".__init__")]
            name = f"{pkg}.{dotted}" if dotted != "__init__" else pkg
            with open(path, encoding="utf-8") as f:
                source = f.read()
            modules.append(Module(path, rel, source, name))
        return cls(modules)

    # -- call resolution ------------------------------------------------------

    def resolve_call(
        self, fn: FunctionInfo, node: ast.Call
    ) -> Optional[FunctionInfo]:
        """Resolve a call site to a package function, or None.

        Order: bare name in the caller's module (then import alias, then
        package-unique bare name); ``self.X`` to a method of the caller's
        class; any other ``….X`` attribute call to a package-unique,
        non-generic method name.  No type inference — ambiguity resolves
        to None (unchecked), never to a guess between candidates.
        """
        name = call_name(node)
        if not name:
            return None
        if "." not in name:
            # a nested def in the CALLER's own scope wins over any
            # same-named def elsewhere in the module (two `_get_fn`s each
            # nesting a `program` must resolve to their own)
            prefix = f"{fn.qualname}.<locals>."
            for cand in self.by_bare_name.get(name, ()):
                if cand.module is fn.module and cand.qualname == (
                    prefix + name
                ):
                    return cand
            local = self._in_module(fn.module, name)
            if local is not None:
                return local
            origin = fn.module.imports.get(name)
            if origin:
                tail = origin.rsplit(".", 1)[-1]
                for cand in self.by_bare_name.get(tail, ()):
                    if origin.startswith(cand.module.name) or "." not in origin:
                        return cand
            return self._unique(name)
        base, _, attr = name.rpartition(".")
        if base == "self" and fn.class_name:
            for cand in self.by_bare_name.get(attr, ()):
                if (
                    cand.class_name == fn.class_name
                    and cand.module is fn.module
                ):
                    return cand
        if attr in GENERIC_NAMES:
            return None
        # a receiver that is an imported EXTERNAL module (np.mean,
        # jnp.concatenate, os.path.join) never resolves into the package
        head = base.split(".")[0]
        origin = fn.module.imports.get(head)
        if origin is not None:
            pkg_root = fn.module.name.split(".")[0]
            if origin.split(".")[0] != pkg_root:
                return None
        return self._unique(attr)

    def _in_module(self, module: Module, name: str) -> Optional[FunctionInfo]:
        for cand in self.by_bare_name.get(name, ()):
            if cand.module is module:
                return cand
        return None

    def _unique(self, name: str) -> Optional[FunctionInfo]:
        if name in GENERIC_NAMES:
            return None
        cands = self.by_bare_name.get(name, ())
        return cands[0] if len(cands) == 1 else None


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


class Baseline:
    """Checked-in ledger of accepted findings (with justifications).

    Schema: ``{"entries": [{"rule", "path", "symbol", "message",
    "justification"}]}``.  Matching is by :attr:`Finding.fingerprint`;
    entries and findings must stay in exact 1:1 sync (stale entries fail
    the gate just like new findings, so the ledger can only shrink by
    fixing code and only grow deliberately via ``--update-baseline``).
    """

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(list(data.get("entries", [])))

    @staticmethod
    def _fp(entry: dict) -> str:
        raw = "|".join(
            (
                entry.get("rule", ""),
                entry.get("path", ""),
                entry.get("symbol", ""),
                entry.get("message", ""),
            )
        )
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def split(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """Partition into (new, baselined, stale-entries)."""
        by_fp = {self._fp(e): e for e in self.entries}
        new: List[Finding] = []
        matched: List[Finding] = []
        seen: Set[str] = set()
        for f in findings:
            if f.fingerprint in by_fp:
                matched.append(f)
                seen.add(f.fingerprint)
            else:
                new.append(f)
        stale = [e for fp, e in by_fp.items() if fp not in seen]
        return new, matched, stale

    @classmethod
    def from_findings(
        cls, findings: Sequence[Finding], justification: str = "TODO: justify"
    ) -> "Baseline":
        entries = [
            {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
                "justification": justification,
            }
            for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line))
        ]
        return cls(entries)

    def updated(
        self,
        findings: Sequence[Finding],
        active_rules: Set[str],
        analyzed_paths: Set[str],
    ) -> "Baseline":
        """The --update-baseline result: accept ``findings``, preserve the
        justifications of entries that still fire, and carry over UNTOUCHED
        every entry outside this run's scope — a rule that wasn't selected
        or a path that wasn't analyzed.  Without the carry-over, a scoped
        ``--rules``/sub-path update would silently destroy every other
        justified entry."""
        keep_just = {
            self._fp(e): e.get("justification", "") for e in self.entries
        }
        out = Baseline.from_findings(findings)
        for e in out.entries:
            j = keep_just.get(self._fp(e))
            if j:
                e["justification"] = j
        fresh = {self._fp(e) for e in out.entries}
        for e in self.entries:
            if self._fp(e) in fresh:
                continue
            if (
                e.get("rule") not in active_rules
                or e.get("path") not in analyzed_paths
            ):
                out.entries.append(e)
        out.entries.sort(
            key=lambda e: (e.get("rule", ""), e.get("path", ""),
                           e.get("symbol", ""), e.get("message", ""))
        )
        return out

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"entries": self.entries}, f, indent=2, sort_keys=True)
            f.write("\n")


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def all_checkers() -> Dict[str, object]:
    """Rule name -> checker instance (import here to avoid cycles)."""
    from docqa_tpu.analysis.cv_protocol import CvProtocolChecker
    from docqa_tpu.analysis.deadline_flow import DeadlineFlowChecker
    from docqa_tpu.analysis.dispatch_streams import DispatchStreamsChecker
    from docqa_tpu.analysis.donation import DonationChecker
    from docqa_tpu.analysis.dtype_flow import DtypeFlowChecker
    from docqa_tpu.analysis.entropy_state import EntropyStateChecker
    from docqa_tpu.analysis.guarded_state import GuardedStateChecker
    from docqa_tpu.analysis.host_sync import HostSyncChecker
    from docqa_tpu.analysis.jit_purity import JitPurityChecker
    from docqa_tpu.analysis.lock_discipline import LockDisciplineChecker
    from docqa_tpu.analysis.mesh_axes import MeshAxesChecker
    from docqa_tpu.analysis.order_stability import OrderStabilityChecker
    from docqa_tpu.analysis.phi_taint import PhiTaintChecker
    from docqa_tpu.analysis.replay_keys import ReplayKeyChecker
    from docqa_tpu.analysis.resource_flow import ResourceFlowChecker
    from docqa_tpu.analysis.retire_once import RetireOnceChecker
    from docqa_tpu.analysis.retrace_hazard import RetraceHazardChecker
    from docqa_tpu.analysis.rng_discipline import RngDisciplineChecker
    from docqa_tpu.analysis.shed_taxonomy import ShedTaxonomyChecker
    from docqa_tpu.analysis.spec_shape import SpecShapeChecker
    from docqa_tpu.analysis.thread_lifecycle import ThreadLifecycleChecker
    from docqa_tpu.analysis.wire_consumer import WireConsumerChecker
    from docqa_tpu.analysis.wire_safety import WireSafetyChecker
    from docqa_tpu.analysis.wire_schema import WireSchemaChecker

    checkers = [
        CvProtocolChecker(),
        DeadlineFlowChecker(),
        DispatchStreamsChecker(),
        DonationChecker(),
        DtypeFlowChecker(),
        EntropyStateChecker(),
        GuardedStateChecker(),
        HostSyncChecker(),
        JitPurityChecker(),
        LockDisciplineChecker(),
        MeshAxesChecker(),
        OrderStabilityChecker(),
        PhiTaintChecker(),
        ReplayKeyChecker(),
        ResourceFlowChecker(),
        RetireOnceChecker(),
        RetraceHazardChecker(),
        RngDisciplineChecker(),
        ShedTaxonomyChecker(),
        SpecShapeChecker(),
        ThreadLifecycleChecker(),
        WireConsumerChecker(),
        WireSafetyChecker(),
        WireSchemaChecker(),
    ]
    return {c.rule: c for c in checkers}


def _run_package(
    package: Package, rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    checkers = all_checkers()
    selected = list(rules) if rules else sorted(checkers)
    unknown = [r for r in selected if r not in checkers]
    if unknown:
        raise ValueError(
            f"unknown rule(s): {', '.join(unknown)} "
            f"(available: {', '.join(sorted(checkers))})"
        )
    by_path = {m.relpath: m for m in package.modules}
    findings: List[Finding] = []
    for rule in selected:
        for f in checkers[rule].check(package):  # type: ignore[attr-defined]
            module = by_path.get(f.path)
            if module is not None and module.is_suppressed(f.rule, f.line):
                continue
            findings.append(f)
    return findings


def run(
    root: str,
    rules: Optional[Iterable[str]] = None,
    package_name: Optional[str] = None,
) -> List[Finding]:
    """Run the selected checkers over ``root``; returns findings with
    per-line suppressions already applied, sorted by (path, line)."""
    findings, _ = analyze_paths([root], rules=rules, package_name=package_name)
    return findings


def analyze_paths(
    paths: Sequence[str],
    rules: Optional[Iterable[str]] = None,
    package_name: Optional[str] = None,
) -> Tuple[List[Finding], Set[str]]:
    """Run the checkers over several roots in ONE parse pass; returns
    (findings, analyzed module relpaths).  The relpath set defines the
    run's scope for baseline staleness and scoped updates."""
    findings: List[Finding] = []
    analyzed: Set[str] = set()
    for path in paths:
        package = Package.load(path, package_name=package_name)
        analyzed |= {m.relpath for m in package.modules}
        findings.extend(_run_package(package, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, analyzed


def default_baseline_path() -> str:
    """The checked-in baseline: ``<repo>/lint_baseline.json`` (repo root =
    parent of the ``docqa_tpu`` package directory)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), "lint_baseline.json")
