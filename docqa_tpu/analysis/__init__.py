"""docqa-lint: AST invariant analysis for the docqa_tpu tree.

Seven project-specific checkers (docs/STATIC_ANALYSIS.md):

* ``deadline-flow``   — request deadlines thread through; waits clamp.
* ``donation``        — buffers donated to jitted calls aren't read after.
* ``jit-purity``      — no side effects / host syncs in traced code.
* ``lock-discipline`` — one lock order; no blocking I/O under a lock.
* ``mesh-axes``       — sharding/collective axis names resolve to the
  declared mesh; collectives stay inside their ``shard_map``.
* ``phi-taint``       — raw pre-deid text never reaches logs/metrics/
  external payloads.
* ``spec-shape``      — PartitionSpec arity matches the annotated rank.

Tier B lives in ``analysis/shard_audit.py`` (docs/SHARDING.md): lower the
device-plane programs on virtual meshes and hold their collective counts
to the checked-in ``shard_budget.json``.

Entry points: ``scripts/lint.py`` / ``scripts/shard_audit.py`` (CLIs) and
``pytest -m lint`` (tier-1 gate, tests/test_analysis.py,
tests/test_shardcheck.py, tests/test_shard_audit.py).
"""

from docqa_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Package,
    all_checkers,
    analyze_paths,
    default_baseline_path,
    run,
)
