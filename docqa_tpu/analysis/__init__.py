"""docqa-lint: AST invariant analysis for the docqa_tpu tree.

Fourteen project-specific checkers (docs/STATIC_ANALYSIS.md):

* ``cv-protocol``     — condition waits in predicate loops, notify under
  the lock, request-path waits carry a Deadline.
* ``deadline-flow``   — request deadlines thread through; waits clamp.
* ``dispatch-streams``— thread entry points that can reach a jax dispatch
  are ledgered in ``dispatch_streams.json`` under a concurrency budget.
* ``donation``        — buffers donated to jitted calls aren't read after.
* ``dtype-flow``      — bf16/int8 matmuls accumulate f32; bf16 reductions
  upcast; no float64 / silent widening in device code.
* ``guarded-state``   — a field written under a lock anywhere is accessed
  under that lock everywhere (per-class + cross-object bridge facts).
* ``host-sync``       — no blocking device→host syncs on the /ask path
  outside jit (jit-purity's deliberate blind spot).
* ``jit-purity``      — no side effects / host syncs in traced code.
* ``lock-discipline`` — one lock order (full-DFS cycles over a transitive
  acquisition graph); no blocking I/O under a lock.
* ``mesh-axes``       — sharding/collective axis names resolve to the
  declared mesh; collectives stay inside their ``shard_map``.
* ``phi-taint``       — raw pre-deid text never reaches logs/metrics/
  external payloads.
* ``retrace-hazard``  — jit wrappers are built once and reused; static
  arguments stay hashable and stable.
* ``spec-shape``      — PartitionSpec arity matches the annotated rank.
* ``thread-lifecycle``— every thread has a reachable join on its owner's
  stop/close path (daemon threads that can reach jax especially).

Tier B lives in ``analysis/shard_audit.py`` (docs/SHARDING.md) — lower
the device-plane programs on virtual meshes, hold their collective counts
to the checked-in ``shard_budget.json`` — in
``analysis/compile_audit.py``: drive the canonical serving workloads
under compile counting, AOT-measure each root's ``memory_analysis()``
bytes, and hold both to ``compile_budget.json`` (zero steady-state
retraces, per-root HBM ceilings) — and in ``analysis/race_witness.py``
(docs/STATIC_ANALYSIS.md "Concurrency witness"): opt-in runtime
instrumentation of lock acquisition whose witnessed order graph is
cross-checked edge-for-edge against lock-discipline's static graph by
the chaos/soak gates.

Entry points: ``scripts/lint.py`` / ``scripts/shard_audit.py`` /
``scripts/compile_audit.py`` / ``scripts/serve_cluster_loop.py`` (CLIs)
and ``pytest -m lint`` (tier-1 gate, tests/test_analysis.py,
tests/test_numcheck.py, tests/test_shardcheck.py, tests/test_racecheck.py,
tests/test_shard_audit.py, tests/test_compile_audit.py).
"""

from docqa_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Package,
    all_checkers,
    analyze_paths,
    default_baseline_path,
    run,
)
