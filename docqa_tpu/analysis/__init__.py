"""docqa-lint: AST invariant analysis for the docqa_tpu tree.

Twenty-four project-specific checkers (docs/STATIC_ANALYSIS.md):

* ``cv-protocol``     — condition waits in predicate loops, notify under
  the lock, request-path waits carry a Deadline.
* ``deadline-flow``   — request deadlines thread through; waits clamp.
* ``dispatch-streams``— thread entry points that can reach a jax dispatch
  are ledgered in ``dispatch_streams.json`` under a concurrency budget.
* ``donation``        — buffers donated to jitted calls aren't read after.
* ``dtype-flow``      — bf16/int8 matmuls accumulate f32; bf16 reductions
  upcast; no float64 / silent widening in device code.
* ``entropy-in-state``— no wall-clock/uuid/urandom values in cache keys,
  prefix keys, or replayed journal records; telemetry timestamp fields
  are sanctioned by naming convention.
* ``guarded-state``   — a field written under a lock anywhere is accessed
  under that lock everywhere (per-class + cross-object bridge facts).
* ``host-sync``       — no blocking device→host syncs on the /ask path
  outside jit (jit-purity's deliberate blind spot).
* ``jit-purity``      — no side effects / host syncs in traced code.
* ``lock-discipline`` — one lock order (full-DFS cycles over a transitive
  acquisition graph); no blocking I/O under a lock.
* ``mesh-axes``       — sharding/collective axis names resolve to the
  declared mesh; collectives stay inside their ``shard_map``.
* ``order-stability`` — set/listdir/glob iteration (and dict iteration
  inside order-sink functions) feeding pack order, batch assembly, key
  construction, or journal serialization must be sorted or justified
  via ``# docqa-lint: ordered(<reason>)``.
* ``phi-taint``       — raw pre-deid text never reaches logs/metrics/
  external payloads.
* ``replay-key-integrity`` — no builtin ``hash()`` of str/bytes in
  cross-restart-persistent keys (per-process hash salting); hashlib/
  crc32/pure-integer arithmetic are the sanctioned derivations.
* ``resource-flow``   — every acquired resource (KV block table, cost
  record, spine ticket, trace) reaches exactly one release on every
  control-flow path: leak-on-exception-edge, double-release, and
  release-of-unacquired are findings.
* ``retire-once``     — every request path hits exactly one retirement
  site; terminal sites are ledgered in ``retirement_sites.json``
  (stale entries fail).
* ``retrace-hazard``  — jit wrappers are built once and reused; static
  arguments stay hashable and stable.
* ``rng-discipline``  — jax.random keys are affine on the serving path
  (consume once, then split/fold_in); no literal ``PRNGKey`` reachable
  from the request path (per-request keys come from the counter-minted
  scheme); no module-global numpy/``random`` RNG on device-result or
  replay-key paths.
* ``shed-taxonomy``   — every raise reachable from the request path is a
  ledgered typed shed in ``shed_taxonomy.json`` carrying its declared
  HTTP status, cost outcome, and trace flag; bare ``Exception`` raises
  and subtype-swallowing catches are findings.
* ``spec-shape``      — PartitionSpec arity matches the annotated rank.
* ``thread-lifecycle``— every thread has a reachable join on its owner's
  stop/close path (daemon threads that can reach jax especially).
* ``wire-consumer``   — every subscript/``.get`` read of an HTTP
  response, broker body, journal record, or bench dotted path resolves
  to a declared producer key; orphaned producer keys also flag.
* ``wire-safety``     — device arrays, numpy scalars, locks, Trace/Span
  objects, and non-finite floats at serialization boundaries
  (``json_response`` / broker publish / journal write) are findings;
  ``to_wire()`` coercion sanctions the site.
* ``wire-schema``     — each route handler's response key tree, derived
  from the AST, matches its ``api_contract.json`` entry (per-endpoint
  versioning; NEW, REMOVED, and STALE keys all fail; pydantic models in
  service/schemas.py must mirror their endpoint's contract).

Tier B lives in ``analysis/shard_audit.py`` (docs/SHARDING.md) — lower
the device-plane programs on virtual meshes, hold their collective counts
to the checked-in ``shard_budget.json`` — in
``analysis/compile_audit.py``: drive the canonical serving workloads
under compile counting, AOT-measure each root's ``memory_analysis()``
bytes, and hold both to ``compile_budget.json`` (zero steady-state
retraces, per-root HBM ceilings) — in ``analysis/race_witness.py``
(docs/STATIC_ANALYSIS.md "Concurrency witness"): opt-in runtime
instrumentation of lock acquisition whose witnessed order graph is
cross-checked edge-for-edge against lock-discipline's static graph by
the chaos/soak gates — and in ``analysis/ledger_audit.py``
(docs/STATIC_ANALYSIS.md "Ledger witness"): opt-in runtime
instrumentation of KV-table / cost-record lifecycle events whose
witnessed acquire sites are cross-checked against resource-flow's
static protocol table, failing on leaks, unretired records, or static
blind spots — and in ``analysis/wire_audit.py`` (docs/STATIC_ANALYSIS.md
"Wire contract"): boot the fake-mode runtime, drive every registered
route over real HTTP, validate each live response key tree and JSON
types against ``api_contract.json``, and round-trip a broker journal
across a simulated restart — and in ``analysis/replay_audit.py``
(docs/STATIC_ANALYSIS.md "Replay witness"): run the deterministic CPU
smoke twice under identical seeds but different hash salts and gate on
bitwise equality of token streams, retrieval ids, journal replay, and
the shadow-sampler selection, with every entropy source in the tree
ledgered and justified in ``determinism_manifest.json``.

Entry points: ``scripts/lint.py`` / ``scripts/shard_audit.py`` /
``scripts/compile_audit.py`` / ``scripts/serve_cluster_loop.py`` /
``scripts/ledger_audit.py`` / ``scripts/wire_audit.py`` /
``scripts/replay_audit.py`` (CLIs) and
``pytest -m lint`` (tier-1 gate, tests/test_analysis.py,
tests/test_numcheck.py, tests/test_shardcheck.py,
tests/test_racecheck.py, tests/test_shard_audit.py,
tests/test_compile_audit.py, tests/test_lifecheck.py,
tests/test_wirecheck.py, tests/test_detcheck.py).
"""

from docqa_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Package,
    all_checkers,
    analyze_paths,
    default_baseline_path,
    run,
)
