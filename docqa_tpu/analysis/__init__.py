"""docqa-lint: AST invariant analysis for the docqa_tpu tree.

Four project-specific checkers (docs/STATIC_ANALYSIS.md):

* ``deadline-flow``   — request deadlines thread through; waits clamp.
* ``jit-purity``      — no side effects / host syncs in traced code.
* ``lock-discipline`` — one lock order; no blocking I/O under a lock.
* ``phi-taint``       — raw pre-deid text never reaches logs/metrics/
  external payloads.

Entry points: ``scripts/lint.py`` (CLI) and ``pytest -m lint``
(tier-1 gate, tests/test_analysis.py).
"""

from docqa_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Finding,
    Package,
    all_checkers,
    analyze_paths,
    default_baseline_path,
    run,
)
