"""Tier B wire audit: measured contract enforcement over real HTTP.

The static wire rules reason about dict literals; this module boots the
fake-mode runtime, drives **every** registered route over a real TCP
socket, and validates each live response — status code, key tree, and
JSON leaf types — against ``api_contract.json``.  The two tiers fail
independently: a handler whose payload the static derivation cannot see
(built by a helper, mutated downstream) still cannot drift, because the
bytes on the wire are re-parsed and re-checked here; conversely a
``--write-*`` style edit to the ledger cannot launder drift past the
static pass, mirroring the compile-/shard-/ledger-audit pattern.

Three measured gates:

* **endpoint coverage** — the driven set, the app's registered route
  table, and the contract's entries must agree exactly (100% coverage
  both directions); a route added to ``make_app`` without a driver and
  a contract entry is a failure by construction.
* **response validation** — 200-JSON bodies validate against the
  entry's ``response`` tree (``open`` entries tolerate extras),
  non-200s against the shared ``error_response`` shape, ``kind``
  routes (html / prometheus-text / sse) against their media contract;
  SSE streams are parsed event-by-event.
* **journal round-trip** — a broker journal is written, the broker is
  torn down, and a fresh broker replays it: surviving depth, body
  equality, and per-record ``journal_record`` conformance are asserted
  across the simulated restart.

Entry point: ``scripts/wire_audit.py`` (blocking in CI);
``run_wire_audit()`` is importable for tests.  ``render_api_md()``
generates ``docs/API.md`` from the contract — a stale generation is a
test failure, so the human-readable reference cannot drift either.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from docqa_tpu.analysis.wire_schema import (
    LEDGER_NAME,
    default_ledger_path,
    load_contract,
)

_SCALARS = {
    "str": (str,),
    "int": (int,),
    "float": (float,),
    "number": (int, float),
    "bool": (bool,),
}
NONFINITE_KEY = "_nonfinite_fields"


# ---------------------------------------------------------------------------
# value validation
# ---------------------------------------------------------------------------


def _leaf_ok(value: Any, leaf: str) -> bool:
    for alt in leaf.split("|"):
        alt = alt.strip()
        if alt == "any":
            return True
        if alt == "null":
            if value is None:
                return True
            continue
        types = _SCALARS.get(alt)
        if types is None:
            continue
        if isinstance(value, bool) and alt != "bool":
            continue  # bool is an int subclass; don't let it pass as int
        if isinstance(value, types):
            return True
    return False


def validate_value(
    value: Any,
    spec: Any,
    open_: bool = False,
    path: str = "$",
) -> List[str]:
    """Violations of ``value`` against a contract spec node."""
    out: List[str] = []
    if isinstance(spec, str):
        if not _leaf_ok(value, spec):
            out.append(
                f"{path}: expected {spec}, got "
                f"{type(value).__name__} ({value!r:.80})"
            )
        return out
    if isinstance(spec, list):
        if not isinstance(value, list):
            out.append(
                f"{path}: expected list, got {type(value).__name__}"
            )
            return out
        elem = spec[0] if spec else "any"
        for i, v in enumerate(value):
            out.extend(validate_value(v, elem, open_, f"{path}[{i}]"))
        return out
    if isinstance(spec, dict):
        if not isinstance(value, dict):
            out.append(
                f"{path}: expected object, got {type(value).__name__}"
            )
            return out
        star = spec.get("*")
        declared: Dict[str, Tuple[Any, bool]] = {}
        for k, sub in spec.items():
            if k == "*":
                continue
            if k.endswith("?"):
                declared[k[:-1]] = (sub, False)
            else:
                declared[k] = (sub, True)
        for k, (sub, required) in declared.items():
            if k in value:
                out.extend(
                    validate_value(value[k], sub, open_, f"{path}.{k}")
                )
            elif required:
                out.append(f"{path}: missing required key '{k}'")
        for k, v in value.items():
            if k in declared or k == NONFINITE_KEY:
                continue
            if star is not None:
                out.extend(validate_value(v, star, open_, f"{path}.{k}"))
            elif not open_:
                out.append(f"{path}: undeclared key '{k}'")
        return out
    out.append(f"{path}: malformed spec node {spec!r}")
    return out


def validate_response(
    entry: Dict[str, Any], status: int, body: Any
) -> List[str]:
    """Status + body of one live response against its contract entry."""
    allowed = entry.get("statuses", [200])
    if status not in allowed:
        return [f"$: status {status} not in declared {allowed}"]
    if status != 200:
        return validate_value(body, {"detail": "str"}, False)
    spec = entry.get("response")
    if spec is None:
        return []
    return validate_value(body, spec, bool(entry.get("open")))


# ---------------------------------------------------------------------------
# docs/API.md generation
# ---------------------------------------------------------------------------


def _spec_lines(spec: Any, indent: int = 0) -> List[str]:
    pad = "  " * indent
    if isinstance(spec, str):
        return [f"{pad}- `{spec}`"]
    if isinstance(spec, list):
        elem = spec[0] if spec else "any"
        if isinstance(elem, str):
            return [f"{pad}- list of `{elem}`"]
        return [f"{pad}- list of:"] + _spec_lines(elem, indent + 1)
    if isinstance(spec, dict):
        lines = []
        for k, sub in spec.items():
            label = (
                "any other key"
                if k == "*"
                else f"`{k[:-1]}` *(optional)*"
                if k.endswith("?")
                else f"`{k}`"
            )
            if isinstance(sub, str):
                lines.append(f"{pad}- {label}: `{sub}`")
            else:
                lines.append(f"{pad}- {label}:")
                lines.extend(_spec_lines(sub, indent + 1))
        return lines
    return [f"{pad}- (malformed spec)"]


def render_api_md(contract: Dict[str, Any]) -> str:
    """Deterministic markdown endpoint reference from the contract.

    ``docs/API.md`` must equal this function's output byte-for-byte
    (tests/test_wirecheck.py) — regenerate with
    ``python scripts/wire_audit.py --write-api-docs``.
    """
    lines = [
        "# HTTP API reference",
        "",
        "Generated from `api_contract.json` by `scripts/wire_audit.py "
        "--write-api-docs` — do not edit by hand; a stale generation "
        "is a test failure.",
        "",
        "Every non-200 JSON response has the shape "
        "`{\"detail\": str}`.  `_nonfinite_fields` (a list of dotted "
        "paths whose non-finite floats were nulled by the boundary "
        "coercion) may appear in any object.  Contract grammar and the "
        "amendment workflow: docs/STATIC_ANALYSIS.md, \"Wire contract "
        "& live audit\".",
        "",
    ]
    for key, entry in contract.get("endpoints", {}).items():
        lines.append(f"## `{key}`")
        lines.append("")
        lines.append(
            f"Handler `{entry.get('handler', '?')}` · contract "
            f"version {entry.get('version', '?')}"
            + (
                f" · pydantic model `{entry['model']}`"
                if entry.get("model")
                else ""
            )
        )
        lines.append("")
        statuses = entry.get("statuses", [200])
        lines.append(
            "Statuses: " + ", ".join(f"`{s}`" for s in statuses)
        )
        lines.append("")
        kind = entry.get("kind")
        if kind is not None:
            lines.append(f"Body: non-JSON (`{kind}`).")
            events = entry.get("events")
            if events:
                lines.append("")
                lines.append("SSE events:")
                for ev, spec in events.items():
                    lines.append(f"- `{ev}`:")
                    lines.extend(_spec_lines(spec, 1))
            lines.append("")
            continue
        spec = entry.get("response")
        if spec is None:
            lines.append("Body: unspecified.")
        else:
            openness = (
                " (open: undeclared extra keys tolerated)"
                if entry.get("open")
                else ""
            )
            lines.append(f"200 body{openness}:")
            lines.append("")
            lines.extend(_spec_lines(spec))
        lines.append("")
    jr = contract.get("journal_record")
    if jr is not None:
        lines.append("## Broker journal record")
        lines.append("")
        lines.extend(_spec_lines(jr))
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def default_api_md_path() -> str:
    return os.path.join(
        os.path.dirname(default_ledger_path()), "docs", "API.md"
    )


# ---------------------------------------------------------------------------
# journal round-trip
# ---------------------------------------------------------------------------


def journal_roundtrip(
    journal_dir: Optional[str] = None,
    contract: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Publish → ack → close → replay in a fresh broker; validate the
    journal lines against ``journal_record`` and the surviving message
    against the original body."""
    from docqa_tpu.service.broker import MemoryBroker

    if contract is None:
        contract = load_contract(default_ledger_path())
    spec = contract.get("journal_record", {"*": "any"})
    violations: List[str] = []
    owns_dir = journal_dir is None
    tmp = journal_dir or tempfile.mkdtemp(prefix="wire_journal_")
    queue = "wire_audit_q"
    body_kept = {"doc_id": "wire-1", "n": 2}
    body_acked = {"doc_id": "wire-0", "n": 1}
    try:
        broker = MemoryBroker(journal_dir=tmp)
        broker.publish(queue, body_acked)
        broker.publish(queue, body_kept, headers={"x-trace": "t-1"})
        d = broker.get(queue, timeout=1.0)
        if d is None:
            violations.append("journal: first delivery never arrived")
        else:
            broker.ack(d)
        broker.close()
        path = os.path.join(tmp, f"{queue}.jsonl")
        if not os.path.exists(path):
            violations.append(f"journal: {path} was never written")
        else:
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        violations.append(
                            f"journal[{i}]: line is not JSON"
                        )
                        continue
                    violations.extend(
                        validate_value(rec, spec, False, f"journal[{i}]")
                    )
        # the simulated restart: a fresh broker replays the journal
        broker2 = MemoryBroker(journal_dir=tmp)
        depth = broker2.depth(queue)
        if depth != 1:
            violations.append(
                f"journal: replayed depth {depth}, expected 1 "
                "(one published message was acked)"
            )
        d2 = broker2.get(queue, timeout=1.0)
        if d2 is None:
            violations.append("journal: replayed message not deliverable")
        elif d2.body != body_kept:
            violations.append(
                f"journal: replayed body {d2.body!r} != published "
                f"{body_kept!r}"
            )
        else:
            broker2.ack(d2)
        broker2.close()
    finally:
        if owns_dir:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    return {"ok": not violations, "violations": violations}


# ---------------------------------------------------------------------------
# live HTTP drive
# ---------------------------------------------------------------------------

FAKE_OVERRIDES = {
    "flags.use_fake_llm": True,
    "flags.use_fake_encoder": True,
    "encoder.embed_dim": 64,
    "store.dim": 64,
    "store.shard_capacity": 256,
    "ner.hidden_dim": 32,
    "ner.num_layers": 1,
    "ner.num_heads": 2,
    "ner.mlp_dim": 64,
    "ner.train_steps": 0,
}

_DOC_TEXT = (
    "Aspirin 100 mg daily. BP 130/85 mmHg. Follow-up in 3 months."
)


def _parse_sse(text: str) -> List[Tuple[str, Any]]:
    """-> [(event name, decoded data)]; default event name is 'data'."""
    events: List[Tuple[str, Any]] = []
    name = "data"
    for block in text.split("\n\n"):
        name = "data"
        data_lines = []
        for line in block.split("\n"):
            if line.startswith("event:"):
                name = line.split(":", 1)[1].strip()
            elif line.startswith("data:"):
                data_lines.append(line.split(":", 1)[1].strip())
        if data_lines:
            try:
                payload = json.loads("\n".join(data_lines))
            except ValueError:
                payload = None
            events.append((name, payload))
    return events


async def _drive(
    rt,
    contract: Dict[str, Any],
    only: Optional[List[str]],
) -> Tuple[Dict[str, Any], List[str]]:
    import aiohttp
    from aiohttp import web

    from docqa_tpu.service.app import make_app

    endpoints = contract.get("endpoints", {})
    results: Dict[str, Any] = {}
    registered: List[str] = []

    app = make_app(rt)
    for route in app.router.routes():
        method = route.method.upper()
        if method not in ("GET", "POST", "PUT", "DELETE", "PATCH"):
            continue
        canonical = route.resource.canonical if route.resource else None
        if canonical is None:
            continue
        registered.append(f"{method} {canonical}")
    registered = sorted(set(registered))

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"

    def record(key: str, status: int, violations: List[str]) -> None:
        if only is not None and key not in only:
            return
        slot = results.setdefault(
            key, {"status": status, "violations": []}
        )
        slot["status"] = status
        slot["violations"].extend(violations)

    async def drive_json(
        key: str,
        path: str,
        s: "aiohttp.ClientSession",
        json_body: Any = None,
    ):
        """Drive one endpoint, validate, and return (status, body)."""
        entry = endpoints.get(key)
        method = key.split(" ", 1)[0]
        async with s.request(method, f"{base}{path}", json=json_body) as r:
            status = r.status
            try:
                body = await r.json()
            except Exception:
                body = None
        if entry is None:
            record(key, status, [f"$: no {LEDGER_NAME} entry"])
        else:
            record(key, status, validate_response(entry, status, body))
        return status, body

    async def drive_text(key: str, path: str, s, expect_ct: str):
        entry = endpoints.get(key, {})
        async with s.get(f"{base}{path}") as r:
            status = r.status
            text = await r.text()
            ct = r.headers.get("Content-Type", "")
        violations: List[str] = []
        allowed = entry.get("statuses", [200])
        if status not in allowed:
            violations.append(f"$: status {status} not in {allowed}")
        if expect_ct not in ct:
            violations.append(
                f"$: content-type {ct!r} lacks {expect_ct!r}"
            )
        if not text.strip():
            violations.append("$: empty body")
        record(key, status, violations)
        return status, text

    try:
        timeout = aiohttp.ClientTimeout(total=120)
        async with aiohttp.ClientSession(timeout=timeout) as s:
            # documents first: later routes need indexed content
            doc_ids = []
            for i in range(2):
                _, body = await drive_json(
                    "POST /ingest/",
                    "/ingest/?wait=1",
                    s,
                    {
                        "filename": f"wire-{i}.txt",
                        "text": _DOC_TEXT,
                        "patient_id": "p-wire",
                    },
                )
                if isinstance(body, dict) and "doc_id" in body:
                    doc_ids.append(body["doc_id"])
            await drive_json("GET /documents/", "/documents/", s)
            if doc_ids:
                await drive_json(
                    "GET /documents/{doc_id}",
                    f"/documents/{doc_ids[0]}",
                    s,
                )

            # QA + traces
            trace_id = None
            entry = endpoints.get("POST /ask/")
            async with s.post(
                f"{base}/ask/", json={"question": "aspirin dose?"}
            ) as r:
                status = r.status
                trace_id = r.headers.get("X-Trace-Id")
                try:
                    body = await r.json()
                except Exception:
                    body = None
            record(
                "POST /ask/",
                status,
                validate_response(entry, status, body)
                if entry
                else [f"$: no {LEDGER_NAME} entry"],
            )
            if trace_id:
                await drive_json(
                    "GET /api/trace/{trace_id}",
                    f"/api/trace/{trace_id}",
                    s,
                )
                await drive_json(
                    "GET /api/trace/{trace_id}",
                    f"/api/trace/{trace_id}?format=chrome",
                    s,
                )
            await drive_json("GET /api/traces", "/api/traces?limit=20", s)

            # SSE stream
            sse_entry = endpoints.get("POST /ask/stream", {})
            async with s.post(
                f"{base}/ask/stream", json={"question": "blood pressure?"}
            ) as r:
                status = r.status
                text = await r.text()
                ct = r.headers.get("Content-Type", "")
            sse_violations: List[str] = []
            if status != 200:
                sse_violations.append(f"$: status {status} != 200")
            if "text/event-stream" not in ct:
                sse_violations.append(f"$: content-type {ct!r} not SSE")
            events = _parse_sse(text)
            if not events:
                sse_violations.append("$: no SSE events parsed")
            declared_events = sse_entry.get("events", {})
            terminal = [n for n, _ in events if n in ("done", "error")]
            if not terminal:
                sse_violations.append("$: stream ended without done/error")
            for name, payload in events:
                spec = declared_events.get(name)
                if spec is None:
                    sse_violations.append(
                        f"$: undeclared SSE event '{name}'"
                    )
                else:
                    sse_violations.extend(
                        validate_value(payload, spec, False, f"$.{name}")
                    )
            record("POST /ask/stream", status, sse_violations)

            # status / metrics / observability
            await drive_json("GET /health", "/health", s)
            await drive_json("GET /api/status", "/api/status", s)
            await drive_text("GET /metrics", "/metrics", s, "text/plain")
            await drive_json("GET /api/metrics", "/api/metrics", s)
            await drive_json("GET /api/telemetry", "/api/telemetry", s)
            await drive_json("GET /api/costs", "/api/costs", s)
            await drive_json(
                "GET /api/costs/sheds", "/api/costs/sheds?limit=20", s
            )
            await drive_json("GET /api/retrieval", "/api/retrieval", s)
            # witness endpoints 404 without the opt-in env instrumentation
            await drive_json("GET /api/witness", "/api/witness", s)
            await drive_json("GET /api/ledger", "/api/ledger", s)

            # pool control plane (404 in fake mode: no rolling_restart)
            await drive_json("GET /api/pool", "/api/pool", s)
            await drive_json(
                "POST /api/pool/drain", "/api/pool/drain?replica=0", s
            )
            await drive_json(
                "POST /api/pool/resume", "/api/pool/resume?replica=0", s
            )
            await drive_json(
                "POST /api/pool/rolling_restart",
                "/api/pool/rolling_restart",
                s,
            )

            # profiler
            await drive_json(
                "POST /api/profiler/start", "/api/profiler/start", s
            )
            await drive_json(
                "POST /api/profiler/stop", "/api/profiler/stop", s
            )

            # clinical surfaces
            await drive_json(
                "GET /api/search/patient-snippets",
                "/api/search/patient-snippets?patient_id=p-wire",
                s,
            )
            await drive_json(
                "POST /api/llm/summarize",
                "/api/llm/summarize",
                s,
                {"prompt": "Summarize the treatment."},
            )
            await drive_json(
                "POST /api/synthese/patient",
                "/api/synthese/patient",
                s,
                {"patient_id": "p-wire"},
            )
            await drive_json(
                "POST /api/synthese/comparaison",
                "/api/synthese/comparaison",
                s,
                {"patient_ids": ["p-wire", "p-ghost"]},
            )

            # teardown of one doc + the index page last
            if len(doc_ids) > 1:
                await drive_json(
                    "DELETE /documents/{doc_id}",
                    f"/documents/{doc_ids[1]}?erase=1",
                    s,
                )
            await drive_text("GET /", "/", s, "text/html")
    finally:
        await runner.cleanup()
    return results, registered


def run_wire_audit(
    contract_path: Optional[str] = None,
    report_path: Optional[str] = None,
    only: Optional[List[str]] = None,
    contract: Optional[Dict[str, Any]] = None,
    skip_journal: bool = False,
) -> Dict[str, Any]:
    """Boot the fake-mode runtime, drive the wire, return the report.

    ``only`` restricts driving/validation to the named endpoint keys
    and disables the coverage gates (for focused tests);
    ``contract`` overrides the loaded ledger (for drift injection).
    """
    import asyncio

    import jax

    jax.config.update("jax_platforms", "cpu")

    from docqa_tpu.config import load_config
    from docqa_tpu.service.app import DocQARuntime

    if contract is None:
        contract = load_contract(contract_path or default_ledger_path())
    endpoints = contract.get("endpoints", {})

    cfg = load_config(env={}, overrides=dict(FAKE_OVERRIDES))
    rt = DocQARuntime(cfg).start()
    try:
        results, registered = asyncio.run(_drive(rt, contract, only))
    finally:
        rt.stop()

    coverage: Dict[str, Any] = {"checked": only is None}
    violations_total = sum(
        len(r["violations"]) for r in results.values()
    )
    if only is None:
        driven = sorted(results)
        declared = sorted(endpoints)
        coverage.update(
            {
                "registered": len(registered),
                "driven": len(driven),
                "declared": len(declared),
                "not_driven": sorted(set(registered) - set(driven)),
                "not_registered": sorted(
                    set(driven) - set(registered)
                ),
                "undeclared_routes": sorted(
                    set(registered) - set(declared)
                ),
                "stale_entries": sorted(
                    set(declared) - set(registered)
                ),
            }
        )
        for k in (
            "not_driven",
            "not_registered",
            "undeclared_routes",
            "stale_entries",
        ):
            if coverage[k]:
                violations_total += len(coverage[k])

    journal = (
        {"ok": True, "violations": [], "skipped": True}
        if skip_journal
        else journal_roundtrip(contract=contract)
    )
    violations_total += len(journal["violations"])

    report = {
        "ok": violations_total == 0,
        "violations_total": violations_total,
        "coverage": coverage,
        "journal": journal,
        "endpoints": {
            k: results[k] for k in sorted(results)
        },
    }
    if report_path:
        with open(report_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    return report
