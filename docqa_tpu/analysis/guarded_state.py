"""guarded-state: a field guarded by a lock anywhere is guarded everywhere.

PR 6 found its four data races by stress, not by lint, because no rule
reasoned about *which lock guards which state*: ``serve.drain()`` judged
quiescence from fields the worker mutates outside the CV, the hedge
bookkeeping was popped under no lock while a waiter read it, and the
rolling-restart teardown raced a monitor tick over replica state.  This
rule infers each module's guard discipline and holds every access to it:

* **guard inference** — a field (``self.X`` or ``obj.X``) *written*
  under ``with <lock>`` in any non-``__init__`` method establishes the
  fact "X is guarded by that lock".  Facts are keyed by attribute name
  per MODULE (no type system: ``r.state`` written under
  ``EnginePool._lock`` and read as ``self.state`` in ``_Replica`` is the
  same field, and one module is the blast radius worth flagging);
* **unguarded write** — any other write to X outside the guard flags;
* **unguarded read** — any read of X outside the guard flags (one
  finding per function, not per site — the fix is the same lock either
  way).  Reads/writes in ``__init__`` are construction (happens-before
  publication) and exempt;
* **mixed-lock access** — X written under lock A here and lock B there
  is a field with two owners, i.e. no owner;
* **published reference** — ``return self.X`` of a guarded MUTABLE
  container (assigned a list/dict/set/deque literal or constructor in
  ``__init__``) hands callers a reference they will mutate or iterate
  outside the guard; return a copy taken under the lock instead.

A helper whose every package-resolvable call site sits under the guard
(``serve._pop_free_slots`` — "caller holds self._cv") is analyzed as
holding it.  Locks aliased through ``Condition(self._lock)`` count as
one guard.  Intentional lock-free access (GIL-atomic scalar reads on
operator surfaces, single-reference publishes) belongs in the baseline
with a written justification — that is the point: the exceptions become
enumerable instead of tribal.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.concurrency import (
    canonical,
    discover_locks,
    direct_with_locks,
    held_at_call_sites,
    is_lock_expr,
    known_lock_attrs,
    lock_aliases,
    lock_id_for,
)
from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
)

def _access_root(node: ast.Attribute) -> Optional[str]:
    """'self' / a bare receiver name for one-hop attribute access."""
    if isinstance(node.value, ast.Name):
        return node.value.id
    return None


class GuardedStateChecker:
    rule = "guarded-state"

    def check(self, package: Package) -> List[Finding]:
        decls = discover_locks(package)
        aliases = lock_aliases(decls)
        known_attrs = known_lock_attrs(decls)
        call_site_held = held_at_call_sites(package, known_attrs)
        out: List[Finding] = []

        # per-module pass: facts do not cross files
        by_module: Dict[object, List[FunctionInfo]] = {}
        for fn in package.functions:
            by_module.setdefault(fn.module, []).append(fn)

        for module, fns in by_module.items():
            out.extend(
                self._check_module(
                    module, fns, known_attrs, aliases, call_site_held
                )
            )
        return out

    # -- per module -----------------------------------------------------------

    # receiver methods that MUTATE the container they're called on — a
    # `self._queue.append(req)` under the lock is a guarded write even
    # though the attribute itself is never rebound
    MUTATING_METHODS = frozenset(
        {
            "append", "appendleft", "pop", "popleft", "popitem", "clear",
            "add", "remove", "discard", "update", "extend", "insert",
            "setdefault", "sort",
        }
    )

    def _accesses(
        self,
        fn: FunctionInfo,
        known_attrs: Set[str],
        aliases: Dict[str, str],
        base_held: Set[str],
    ):
        """Yield (root, attr, is_write, held_locks, lineno) for every
        one-hop attribute access in ``fn`` (nested defs excluded — they
        are separate functions with their own call sites).  Writes =
        Store/Del contexts, subscript stores (``self.x[k] = v``), and
        mutating method calls (``self.x.append(v)``)."""
        results: List[Tuple[str, str, bool, Set[str], int]] = []

        # attribute nodes that are written THROUGH (not rebound): the
        # receiver of a mutating method call or of a subscript store
        written_through: Set[int] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                recv = node.func.value
                if (
                    node.func.attr in self.MUTATING_METHODS
                    and isinstance(recv, ast.Attribute)
                ):
                    written_through.add(id(recv))
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                if isinstance(node.value, ast.Attribute):
                    written_through.add(id(node.value))

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                new_held = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        if isinstance(item.context_expr, ast.Call):
                            continue
                        try:
                            text = ast.unparse(item.context_expr)
                        except Exception:
                            continue
                        if is_lock_expr(text, known_attrs):
                            new_held = new_held + (
                                canonical(
                                    lock_id_for(fn, text), aliases
                                ),
                            )
                if isinstance(child, ast.Attribute):
                    root = _access_root(child)
                    if root is not None and child.attr not in known_attrs:
                        is_write = (
                            isinstance(child.ctx, (ast.Store, ast.Del))
                            or id(child) in written_through
                        )
                        results.append(
                            (
                                root,
                                child.attr,
                                is_write,
                                set(new_held) | base_held,
                                child.lineno,
                            )
                        )
                # augmented assignment targets parse as Store only at the
                # target; `self.x += 1` is BOTH a read and a write — the
                # Attribute appears once with Store ctx, which is the
                # stricter of the two, so nothing extra to do
                visit(child, new_held)

        visit(fn.node, ())
        return results

    def _check_module(
        self,
        module,
        fns: List[FunctionInfo],
        known_attrs: Set[str],
        aliases: Dict[str, str],
        call_site_held: Dict[int, Set[str]],
    ) -> List[Finding]:
        # guard facts, two strengths:
        # * class facts — SELF-writes under a lock, keyed (class, attr):
        #   a class's own discipline binds its own accesses only (two
        #   classes each caching a `_fns` under their own lock are not
        #   each other's business);
        # * bridge facts — writes through a NON-self receiver (`r.state`
        #   under the pool lock), keyed attr module-wide, kept only when
        #   some class in the module touches the attr via `self` — the
        #   cross-object pattern (owner class + managing class) the
        #   per-class view cannot see.  Without the self-partner filter,
        #   every `req.error = …` in a locked helper would claim guard
        #   facts over a dataclass whose real ordering contract is the
        #   done-Event, not a lock.
        # each group: list of (held-lock frozenset, line, qualname), one
        # per guarded write site.  The group's GUARD set is the
        # intersection across sites — a write under {A, B} and a write
        # under {A} are consistently guarded by A (flag_window holds the
        # caller's lock AND its own; the recorder lock is the guard),
        # while disjoint sets mean mixed-lock access.
        class_guards: Dict[
            Tuple[Optional[str], str], List[Tuple[frozenset, int, str]]
        ] = {}
        bridge_guards: Dict[str, List[Tuple[frozenset, int, str]]] = {}
        self_touched: Set[str] = set()  # attrs with a self access
        # attr -> was assigned a mutable container in __init__
        mutable_init: Set[str] = set()
        # collected accesses: (fn, root, attr, is_write, held, lineno)
        accesses: List[
            Tuple[FunctionInfo, str, str, bool, Set[str], int]
        ] = []

        for fn in fns:
            base_held = {
                canonical(lid, aliases)
                for lid in call_site_held.get(id(fn.node), set())
            }
            acc = self._accesses(fn, known_attrs, aliases, base_held)
            if fn.name == "__init__":
                # mutable-container detection needs the assigned VALUE
                for node in ast.walk(fn.node):
                    if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = getattr(node, "value", None)
                    if value is None:
                        continue
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    mutable = isinstance(
                        value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                ast.DictComp)
                    )
                    if isinstance(value, ast.Call):
                        tail = ast.unparse(value.func).rsplit(".", 1)[-1]
                        mutable = mutable or tail in (
                            "list", "dict", "set", "deque", "OrderedDict",
                            "defaultdict",
                        )
                    if not mutable:
                        continue
                    for t in targets:
                        if isinstance(t, ast.Attribute) and _access_root(t):
                            mutable_init.add(t.attr)
                continue  # __init__ accesses are construction — exempt
            for root, attr, is_write, held, line in acc:
                accesses.append((fn, root, attr, is_write, held, line))
                if root == "self":
                    self_touched.add(attr)
                if is_write and held:
                    slot = (
                        class_guards.setdefault(
                            (fn.class_name, attr), []
                        )
                        if root == "self"
                        else bridge_guards.setdefault(attr, [])
                    )
                    slot.append((frozenset(held), line, fn.qualname))

        # bridge facts need a self-side partner (see above)
        bridge_guards = {
            attr: sites
            for attr, sites in bridge_guards.items()
            if attr in self_touched
        }

        def guard_set(
            sites: List[Tuple[frozenset, int, str]]
        ) -> Set[str]:
            return set(frozenset.intersection(*[s for s, _l, _q in sites]))

        def facts_for(fn: FunctionInfo, root: str, attr: str) -> Set[str]:
            """Union of the guard sets that bind this access."""
            guards: Set[str] = set()
            if root == "self":
                for sites in (
                    class_guards.get((fn.class_name, attr)),
                    bridge_guards.get(attr),
                ):
                    if sites:
                        guards |= guard_set(sites)
                return guards
            for (_cls, a), sites in class_guards.items():
                if a == attr:
                    guards |= guard_set(sites)
            if attr in bridge_guards:
                guards |= guard_set(bridge_guards[attr])
            return guards

        out: List[Finding] = []
        # mixed-lock writes: a fact group whose write sites share NO lock
        seen_mixed: Set[str] = set()
        groups = list(class_guards.items()) + [
            ((None, attr), sites) for attr, sites in bridge_guards.items()
        ]
        for (_cls, attr), sites in sorted(
            groups, key=lambda kv: (kv[0][1], str(kv[0][0]))
        ):
            if len(sites) > 1 and not guard_set(sites) and (
                attr not in seen_mixed
            ):
                seen_mixed.add(attr)
                ordered = sorted(sites, key=lambda s: s[1])
                (h1, line1, q1) = ordered[0]
                other = next(
                    (s for s in ordered if not (s[0] & h1)), ordered[1]
                )
                out.append(
                    Finding(
                        self.rule,
                        module.relpath,
                        line1,
                        q1,
                        f"field '{attr}' is written under "
                        f"{sorted(h1)[0]} here but under "
                        f"{sorted(other[0])[0]} in {other[2]} (mixed-lock "
                        "access: a field with two guards has none)",
                    )
                )

        # unguarded access to guarded fields: one finding per (attr, fn)
        reported: Set[Tuple[str, str, bool]] = set()
        for fn, root, attr, is_write, held, line in accesses:
            if fn.name.endswith("_locked"):
                # the codebase's caller-holds-the-lock convention: the
                # suffix IS the annotation (call-site inference already
                # proves most of these; the suffix covers mixed callers)
                continue
            guards = facts_for(fn, root, attr)
            if not guards:
                continue
            if guards & held:
                continue
            key = (attr, fn.qualname, is_write)
            if key in reported:
                continue
            reported.add(key)
            guard = sorted(guards)[0]
            verb = "written" if is_write else "read"
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    line,
                    fn.qualname,
                    f"field '{attr}' is guarded by {guard} but {verb} "
                    "without it here",
                )
            )

        # published references: `return self.X` of a guarded mutable field
        for fn in fns:
            if fn.name == "__init__":
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                v = node.value
                if (
                    isinstance(v, ast.Attribute)
                    and _access_root(v) == "self"
                    and facts_for(fn, "self", v.attr)
                    and v.attr in mutable_init
                ):
                    out.append(
                        Finding(
                            self.rule,
                            module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"guarded mutable field '{v.attr}' published "
                            "by reference (callers mutate/iterate it "
                            "outside the guard) — return a copy taken "
                            "under the lock",
                        )
                    )
        return out
