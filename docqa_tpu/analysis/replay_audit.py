"""docqa-detcheck Tier B: the bitwise replay witness.

The four detcheck rules (rng-discipline, replay-key-integrity,
order-stability, entropy-in-state) are static over-approximations; this
module holds the *dynamic* side of the same contract: two smoke runs
under identical seeds — fresh interpreter each, different
``PYTHONHASHSEED`` so salted-hash and set-order bugs cannot hide — must
produce bitwise-identical results.  ``scripts/replay_audit.py`` drives
the runs and calls into here for everything pure:

* :func:`compare_transcripts` — the equality gate over two run
  transcripts: per-request token streams (bitwise), retrieval result
  ids, broker-journal document states across a simulated restart, and
  the recallscope shadow-sampler selection set.  Returns a divergence
  report (first-diverging request, token index, stage attribution) —
  the CI artifact an operator starts from;
* the determinism manifest — ``determinism_manifest.json`` ledgers
  every sanctioned entropy source in the tree (enumerated by
  :func:`docqa_tpu.analysis.entropy.enumerate_entropy_sites`) with a
  human justification.  NEW sites (unledgered entropy) and STALE
  entries (ledgered sites that no longer exist) both fail, exactly like
  the lint baseline; so does any TODO justification.  ``--write-manifest``
  regenerates the ledger but CANNOT launder a divergence: the gate
  re-derives equality from the measurement, and fresh entries carry a
  TODO that itself fails until a human justifies the source.

Stage attribution order follows the request path: a decode divergence
is reported first (it usually *causes* downstream retrieval/journal
diffs in a real serving stack), then retrieval, journal, shadow.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

MANIFEST_FILENAME = "determinism_manifest.json"
_TODO_MARK = "TODO"


def default_manifest_path() -> str:
    """``<repo>/determinism_manifest.json`` (repo root = parent of the
    ``docqa_tpu`` package directory, same convention as the lint
    baseline)."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), MANIFEST_FILENAME)


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def _site_key(entry: Dict[str, Any]) -> Tuple[str, str, str, str]:
    """Manifest identity: (kind, path, symbol, call) — deliberately not
    the line number, so unrelated edits don't churn the ledger."""
    return (
        entry.get("kind", ""),
        entry.get("path", ""),
        entry.get("symbol", ""),
        entry.get("call", ""),
    )


def load_manifest(path: str) -> List[Dict[str, Any]]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("entries", []))


def save_manifest(path: str, entries: Sequence[Dict[str, Any]]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"entries": list(entries)}, f, indent=2, sort_keys=True)
        f.write("\n")


def manifest_split(
    sites: Sequence[Dict[str, Any]], entries: Sequence[Dict[str, Any]]
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Partition into (new-sites, matched-sites, stale-entries)."""
    by_key = {_site_key(e): e for e in entries}
    new: List[Dict[str, Any]] = []
    matched: List[Dict[str, Any]] = []
    seen = set()
    for s in sites:
        key = _site_key(s)
        if key in by_key:
            matched.append(s)
            seen.add(key)
        else:
            new.append(s)
    stale = [e for k, e in by_key.items() if k not in seen]
    return new, matched, stale


def manifest_todos(
    entries: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Entries whose justification is missing or still a TODO — a
    freshly ``--write-manifest``-ed site stays failing until a human
    writes down WHY the entropy source is sanctioned."""
    out = []
    for e in entries:
        j = str(e.get("justification", "")).strip()
        if not j or j.upper().startswith(_TODO_MARK):
            out.append(e)
    return out


def updated_manifest(
    sites: Sequence[Dict[str, Any]],
    old_entries: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """The ``--write-manifest`` result: one entry per current site,
    preserving the justification of every entry that still matches;
    new sites get an explicit TODO (which fails the gate)."""
    keep = {_site_key(e): e.get("justification", "") for e in old_entries}
    out = []
    for s in sites:
        entry = {
            "kind": s["kind"],
            "path": s["path"],
            "symbol": s["symbol"],
            "call": s["call"],
            "justification": keep.get(_site_key(s), "")
            or "TODO: justify this entropy source",
        }
        out.append(entry)
    out.sort(key=_site_key)
    return out


# ---------------------------------------------------------------------------
# transcript comparison
# ---------------------------------------------------------------------------


def _first_token_diff(a: Sequence[int], b: Sequence[int]) -> int:
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            return i
    return min(len(a), len(b))


def _by_id(items: Sequence[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    return {str(r["id"]): r for r in items}


def compare_transcripts(
    run_a: Dict[str, Any], run_b: Dict[str, Any]
) -> Dict[str, Any]:
    """Bitwise equality gate over two smoke transcripts.

    Returns ``{"equal", "divergences", "first_divergence"}``; each
    divergence carries ``stage`` plus stage-specific attribution
    (request id + token index for decode, query id for retrieval,
    queue/doc for journal).
    """
    divergences: List[Dict[str, Any]] = []

    # -- stage: decode (per-request token streams, bitwise) ------------------
    req_a = _by_id(run_a.get("decode", {}).get("requests", []))
    req_b = _by_id(run_b.get("decode", {}).get("requests", []))
    for rid in sorted(set(req_a) | set(req_b)):
        ra, rb = req_a.get(rid), req_b.get(rid)
        if ra is None or rb is None:
            divergences.append(
                {
                    "stage": "decode",
                    "request": rid,
                    "detail": "request present in only one run",
                }
            )
            continue
        ta, tb = list(ra.get("tokens", [])), list(rb.get("tokens", []))
        if ta != tb:
            divergences.append(
                {
                    "stage": "decode",
                    "request": rid,
                    "phase": ra.get("phase"),
                    "token_index": _first_token_diff(ta, tb),
                    "len_a": len(ta),
                    "len_b": len(tb),
                    "detail": "token streams diverge",
                }
            )

    # -- stage: retrieval (result ids, ordered) ------------------------------
    q_a = _by_id(run_a.get("retrieval", {}).get("queries", []))
    q_b = _by_id(run_b.get("retrieval", {}).get("queries", []))
    for qid in sorted(set(q_a) | set(q_b)):
        qa, qb = q_a.get(qid), q_b.get(qid)
        if qa is None or qb is None:
            divergences.append(
                {
                    "stage": "retrieval",
                    "query": qid,
                    "detail": "query present in only one run",
                }
            )
            continue
        if list(qa.get("doc_ids", [])) != list(qb.get("doc_ids", [])):
            divergences.append(
                {
                    "stage": "retrieval",
                    "query": qid,
                    "detail": "retrieval result ids differ",
                    "doc_ids_a": list(qa.get("doc_ids", [])),
                    "doc_ids_b": list(qb.get("doc_ids", [])),
                }
            )

    # -- stage: journal (restart convergence, within and across runs) --------
    for label, run in (("run_a", run_a), ("run_b", run_b)):
        j = run.get("journal", {})
        if j and j.get("doc_states_pre") != j.get("doc_states_post"):
            divergences.append(
                {
                    "stage": "journal",
                    "detail": f"{label}: journal replay did not converge "
                    "to the pre-restart document states",
                }
            )
    ja = run_a.get("journal", {}).get("doc_states_post")
    jb = run_b.get("journal", {}).get("doc_states_post")
    if ja != jb:
        diff_docs = sorted(
            k
            for k in set(ja or {}) | set(jb or {})
            if (ja or {}).get(k) != (jb or {}).get(k)
        )
        divergences.append(
            {
                "stage": "journal",
                "detail": "post-restart document states differ across runs",
                "docs": diff_docs,
            }
        )
    da = run_a.get("journal", {}).get("drained")
    db = run_b.get("journal", {}).get("drained")
    if da != db:
        divergences.append(
            {
                "stage": "journal",
                "detail": "replayed delivery order/content differs "
                "across runs",
            }
        )

    # -- stage: shadow sampler (identical request selection set) -------------
    sa = run_a.get("shadow", {})
    sb = run_b.get("shadow", {})
    if list(sa.get("selected", [])) != list(sb.get("selected", [])):
        divergences.append(
            {
                "stage": "shadow_sampler",
                "detail": "shadow sampler selected different request sets",
                "selected_a": list(sa.get("selected", [])),
                "selected_b": list(sb.get("selected", [])),
            }
        )

    return {
        "equal": not divergences,
        "divergences": divergences,
        "first_divergence": divergences[0] if divergences else None,
    }
