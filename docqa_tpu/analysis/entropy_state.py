"""entropy-in-state: no wall-clock/uuid/urandom values in keys or
replayed records.

Replay reconstructs state from persisted records and re-derives cache
and prefix keys from request content.  A ``time.time()`` / ``uuid4()`` /
``os.urandom()`` value that leaks into a *key* or into a journal field
replay reads back as state can never be re-minted by the second run —
the replay gate diverges (or worse, silently misses: two runs build
different cache keys and the warm path never exercises).  Timestamps in
*telemetry* are fine — the taint stops at declared observability sinks
(metrics/spans/log fields are measurements, not state), and scheduling
or audit fields that follow the timestamp naming convention
(``*_at``/``*_date``/``*_time``/``ts``/``timestamp``) are sanctioned:
replay treats them as data carried in the record, never as identity.

Scope: the state-owning modules (qa keys, serve/paged/pool caches,
broker journal, registry/pipeline records, index stores, observatory);
fixtures opt in with the ``docqa-lint: request-path`` pragma.

Taint sources (via :mod:`docqa_tpu.analysis.entropy`): ``time.time``/
``time_ns``, ``datetime.now``/``utcnow``, ``uuid1``/``uuid4``,
``os.urandom``, ``secrets.*`` — plus the monotonic interval clocks
(``perf_counter``/``monotonic``), which measure durations legitimately
everywhere EXCEPT inside a key.  Propagation is one-level name taint
(assignment from a tainted expression taints the targets; reassignment
from a clean one clears).

Sinks that flag a tainted value:

1. an argument to ``hashlib.*``/``zlib.crc32``/builtin ``hash`` — the
   digest becomes an unreplayable key;
2. a keyword argument whose name contains ``key``;
3. the right-hand side of an assignment to a ``*key*``/``*fingerprint*``
   name (f-strings and concatenation included);
4. a journal/publish record field whose name does NOT follow the
   timestamp convention — replay reads that field back as state;
5. a subscript key on a cache-ish receiver (``*cache*``/``*entries*``/
   ``*table*``) — the entry can never be hit again after restart.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Module,
    Package,
    call_name,
    dotted_name,
)
from docqa_tpu.analysis.entropy import (
    MONOTONIC_CLOCKS,
    classify_entropy_call,
)

STATE_MODULES = frozenset(
    {
        "docqa_tpu.service.qa",
        "docqa_tpu.service.broker",
        "docqa_tpu.service.registry",
        "docqa_tpu.service.pipeline",
        "docqa_tpu.engines.serve",
        "docqa_tpu.engines.paged",
        "docqa_tpu.engines.pool",
        "docqa_tpu.index.store",
        "docqa_tpu.obs.retrieval_observatory",
    }
)

# record fields that carry a timestamp AS DATA (telemetry/scheduling/
# audit) — replay never derives identity or ordering keys from them
_TIMESTAMP_FIELD_RE = re.compile(
    r"(_at|_date|_unix|_ts|_time|_ms|_s)$|^(ts|t0|time|now|timestamp|"
    r"ready_at|deadline)$"
)
_KEYISH_NAME_RE = re.compile(r"key|fingerprint", re.IGNORECASE)
_CACHEISH_RECV_RE = re.compile(r"cache|entries|table", re.IGNORECASE)
_JOURNAL_CALL_TAILS = frozenset({"publish", "_journal_write"})


class EntropyStateChecker:
    rule = "entropy-in-state"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        for fn in package.functions:
            module = fn.module
            if not (
                module.name in STATE_MODULES or module.request_path_pragma
            ):
                continue
            self._scan(fn, out)
        return out

    # -- taint ---------------------------------------------------------------

    def _is_entropy_call(self, module: Module, node: ast.Call) -> bool:
        hit = classify_entropy_call(module, node)
        if hit is not None:
            # rng mints are rng-discipline's rule, not taint-into-state
            return hit[0] in ("process", "wallclock")
        name = call_name(node)
        if not name:
            return False
        return module.resolve_alias(name) in MONOTONIC_CLOCKS

    def _scan(self, fn: FunctionInfo, out: List[Finding]) -> None:
        module = fn.module
        tainted: Set[str] = set()
        # dict-literal names: name -> {field: tainted?} so a record built
        # locally then published still attributes the tainted field
        dict_fields: Dict[str, Dict[str, bool]] = {}

        def add(node, message) -> None:
            out.append(
                Finding(
                    self.rule,
                    module.relpath,
                    getattr(node, "lineno", 1),
                    fn.qualname,
                    message,
                )
            )

        def expr_tainted(node: ast.AST) -> bool:
            stack = [node]
            while stack:
                cur = stack.pop()
                if isinstance(
                    cur,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                if isinstance(cur, ast.Name) and cur.id in tainted:
                    return True
                if isinstance(cur, ast.Call) and self._is_entropy_call(
                    module, cur
                ):
                    return True
                stack.extend(ast.iter_child_nodes(cur))
            return False

        def tainted_dict_fields(node: ast.Dict) -> Dict[str, bool]:
            fields: Dict[str, bool] = {}
            for k, v in zip(node.keys, node.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    continue
                fields[k.value] = expr_tainted(v)
            return fields

        def check_record_fields(call_node, fields, label) -> None:
            for field, is_tainted in fields.items():
                if not is_tainted:
                    continue
                if _TIMESTAMP_FIELD_RE.search(field):
                    continue
                add(
                    call_node,
                    f"record field '{field}' in {label} carries "
                    "wall-clock/uuid/urandom entropy — replay reads this "
                    "record back as state it cannot re-mint; use a "
                    "timestamp-convention field name (*_at/ts) for "
                    "telemetry, or derive the value from request content",
                )

        def check_call_sinks(node: ast.Call) -> None:
            name = call_name(node)
            resolved = module.resolve_alias(name) if name else ""
            tail = name.rsplit(".", 1)[-1] if name else ""
            # sink 1: digests
            if (
                resolved.startswith("hashlib.")
                or resolved == "zlib.crc32"
                or (name == "hash" and "hash" not in module.imports)
            ):
                for arg in node.args:
                    if expr_tainted(arg):
                        add(
                            node,
                            f"entropy flows into {tail}() — the digest "
                            "becomes a key no replayed process can "
                            "re-derive; digest request content, not "
                            "clocks/uuids",
                        )
                        break
            # sink 2: key-named keyword arguments
            for kw in node.keywords:
                if (
                    kw.arg
                    and "key" in kw.arg.lower()
                    and expr_tainted(kw.value)
                ):
                    add(
                        node,
                        f"keyword '{kw.arg}' receives wall-clock/uuid "
                        "entropy — keys must be derivable from request "
                        "content alone",
                    )
            # sink 4: journal/publish record fields
            if tail in _JOURNAL_CALL_TAILS:
                for arg in list(node.args) + [
                    k.value for k in node.keywords
                ]:
                    if isinstance(arg, ast.Dict):
                        check_record_fields(
                            node, tainted_dict_fields(arg), f"{tail}()"
                        )
                    elif (
                        isinstance(arg, ast.Name)
                        and arg.id in dict_fields
                    ):
                        check_record_fields(
                            node, dict_fields[arg.id], f"{tail}()"
                        )

        def handle_expr(node: ast.AST) -> None:
            stack = [node]
            while stack:
                cur = stack.pop()
                if isinstance(
                    cur,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    continue
                if isinstance(cur, ast.Call):
                    check_call_sinks(cur)
                stack.extend(ast.iter_child_nodes(cur))

        def bind_assign(stmt: ast.Assign) -> None:
            value = stmt.value
            is_tainted = expr_tainted(value)
            fields = (
                tainted_dict_fields(value)
                if isinstance(value, ast.Dict)
                else None
            )
            for target in stmt.targets:
                # sink 5: tainted subscript KEY on a cache-ish receiver
                if isinstance(target, ast.Subscript):
                    recv = dotted_name(target.value)
                    if _CACHEISH_RECV_RE.search(recv) and expr_tainted(
                        target.slice
                    ):
                        add(
                            stmt,
                            f"cache/table '{recv}' keyed by a wall-clock/"
                            "uuid value — the entry is unreachable after "
                            "restart; key by request content",
                        )
                    continue
                if not isinstance(target, ast.Name):
                    continue
                # sink 3: key-named variables
                if is_tainted and _KEYISH_NAME_RE.search(target.id):
                    add(
                        stmt,
                        f"'{target.id}' is built from wall-clock/uuid "
                        "entropy — a key that no restarted process can "
                        "re-derive; build it from request content",
                    )
                if is_tainted:
                    tainted.add(target.id)
                else:
                    tainted.discard(target.id)
                if fields is not None:
                    dict_fields[target.id] = fields
                else:
                    dict_fields.pop(target.id, None)

        def walk(stmts) -> None:
            for stmt in stmts:
                if isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if isinstance(stmt, ast.Assign):
                    handle_expr(stmt.value)
                    bind_assign(stmt)
                    continue
                for _name, field in ast.iter_fields(stmt):
                    if isinstance(field, ast.expr):
                        handle_expr(field)
                    elif isinstance(field, list):
                        if field and isinstance(field[0], ast.stmt):
                            walk(field)
                        elif field and isinstance(
                            field[0], ast.excepthandler
                        ):
                            for handler in field:
                                walk(handler.body)
                        elif field and isinstance(field[0], ast.expr):
                            for e in field:
                                handle_expr(e)
                        elif field and isinstance(field[0], ast.withitem):
                            for item in field:
                                handle_expr(item.context_expr)

        body = getattr(fn.node, "body", None)
        if body:
            walk(body)
