"""order-stability: iteration order feeding device packing, key
construction, or journal serialization must be pinned.

Ragged-prefill pack order determines position-dependent numerics: the
bitwise gates (warm==cold, spec on==off) hold only because pack order is
a function of *admission order* alone.  Anything that injects an
unordered iterate upstream of packing, batch assembly, key derivation,
or journal/ledger serialization makes two identical runs diverge:

* ``set``/``frozenset`` iteration order varies per process (str hash
  salting) — flagged wherever it appears in a scope module;
* ``os.listdir``/``os.scandir``/``glob`` order is filesystem-dependent
  (journal replay order must not depend on the directory's inode order)
  — flagged unless wrapped in ``sorted(...)``;
* ``dict`` iteration is insertion-ordered — deterministic only if the
  *insertions* were.  Flagged only inside order-sink functions (name
  matches pack/admis/assemble/serial/journal/key/fingerprint/batch/
  snapshot/replay, or the body writes the journal or a hashlib/json
  digest), where an unjustified iterate is one concurrent insert away
  from breaking replay.

Order pins, checked on the iterate's line: a ``sorted(...)`` wrap, a
prior ``.sort()`` on the name, or the justification pragma
``# docqa-lint: ordered(<why insertion order is deterministic>)`` — the
comment-ledger form for insertion-ordered dicts whose single-writer
discipline the analyzer cannot see.

Scope: the packing/serving engines, the qa/pipeline/broker service
plane, the index stores, and the retrieval observatory; fixtures opt in
with the ``docqa-lint: request-path`` pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Module,
    Package,
    call_name,
)

ORDER_MODULES = frozenset(
    {
        "docqa_tpu.engines.serve",
        "docqa_tpu.engines.paged",
        "docqa_tpu.engines.pool",
        "docqa_tpu.engines.qos",
        "docqa_tpu.service.qa",
        "docqa_tpu.service.pipeline",
        "docqa_tpu.service.broker",
        "docqa_tpu.index.store",
        "docqa_tpu.index.tiered",
        "docqa_tpu.obs.retrieval_observatory",
    }
)

_ORDERED_PRAGMA_RE = re.compile(r"#\s*docqa-lint:\s*ordered\(([^)]*)\)")
_SINK_NAME_RE = re.compile(
    r"pack|admis|admit|assemble|serial|journal|key|fingerprint|batch"
    r"|snapshot|replay",
    re.IGNORECASE,
)
_SINK_CALL_TAILS = frozenset(
    {"_journal_write", "dumps", "sha1", "sha256", "md5", "crc32", "blake2b"}
)
_LISTING_CALLS = frozenset(
    {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
)
_DICT_METHODS = frozenset({"items", "keys", "values"})
_SET_METHODS = frozenset(
    {"difference", "union", "intersection", "symmetric_difference"}
)


def ordered_pragma_lines(module: Module) -> Dict[int, str]:
    """line -> justification text for ``# docqa-lint: ordered(...)``."""
    out: Dict[int, str] = {}
    for i, line in enumerate(module.source.splitlines(), start=1):
        m = _ORDERED_PRAGMA_RE.search(line)
        if m:
            out[i] = m.group(1).strip()
    return out


class OrderStabilityChecker:
    rule = "order-stability"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        for module in package.modules:
            if not (
                module.name in ORDER_MODULES or module.request_path_pragma
            ):
                continue
            pragmas = ordered_pragma_lines(module)
            fns = [
                f for f in package.functions if f.module is module
            ]
            for fn in fns:
                self._scan_fn(module, fn, pragmas, out)
            self._scan_module_level(module, pragmas, out)
        return out

    # -- classification -------------------------------------------------------

    def _classify(
        self, module: Module, node: ast.AST, facts: Dict[str, str]
    ) -> Optional[str]:
        """'set' | 'dict' | 'listing' for an unordered iterable
        expression, None when unknown/pinned."""
        if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
            return "set"
        if isinstance(node, ast.Name):
            return facts.get(node.id)
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
        ):
            return self._classify(
                module, node.left, facts
            ) or self._classify(module, node.right, facts)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if not name:
                return None
            resolved = module.resolve_alias(name)
            tail = name.rsplit(".", 1)[-1]
            if resolved == "sorted" or tail == "sort":
                return None  # pinned
            if resolved in ("set", "frozenset"):
                return "set"
            if resolved in _LISTING_CALLS:
                return "listing"
            if resolved == "dict":
                return "dict"
            if "." in name and tail in _SET_METHODS:
                recv = node.func.value if isinstance(
                    node.func, ast.Attribute
                ) else None
                if (
                    self._classify(module, recv, facts) == "set"
                    if recv is not None
                    else False
                ):
                    return "set"
                return None
            if "." in name and tail in _DICT_METHODS:
                return "dict"
        return None

    def _bind_facts(
        self, module: Module, stmt: ast.Assign, facts: Dict[str, str]
    ) -> None:
        kind = None
        value = stmt.value
        if isinstance(value, (ast.Set, ast.SetComp)):
            kind = "set"
        elif isinstance(value, (ast.Dict, ast.DictComp)):
            kind = "dict"
        elif isinstance(value, ast.Call):
            name = call_name(value)
            resolved = module.resolve_alias(name) if name else ""
            if resolved in ("set", "frozenset"):
                kind = "set"
            elif resolved in ("dict", "collections.OrderedDict"):
                kind = "dict"
            elif resolved in _LISTING_CALLS:
                kind = "listing"
            elif resolved == "sorted":
                kind = None
        for target in stmt.targets:
            if isinstance(target, ast.Name):
                if kind is None:
                    facts.pop(target.id, None)
                else:
                    facts[target.id] = kind

    # -- sink-function detection ----------------------------------------------

    def _is_order_sink(self, module: Module, fn: FunctionInfo) -> bool:
        if _SINK_NAME_RE.search(fn.name):
            return True
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            resolved = module.resolve_alias(name)
            tail = name.rsplit(".", 1)[-1]
            if tail in _SINK_CALL_TAILS or resolved.startswith("hashlib."):
                return True
        return False

    # -- scanning -------------------------------------------------------------

    _MESSAGES = {
        "set": (
            "iterating a set/frozenset into an order-sensitive path — "
            "per-process hash salting makes the order nondeterministic; "
            "wrap in sorted(...)"
        ),
        "listing": (
            "unsorted directory listing — os.listdir/glob order is "
            "filesystem-dependent, so replay/pack order would vary per "
            "host; wrap in sorted(...)"
        ),
        "dict": (
            "dict iteration inside an order sink (packing/key/journal "
            "construction) — insertion order is deterministic only if "
            "the inserts were; wrap in sorted(...) or justify with "
            "# docqa-lint: ordered(<reason>)"
        ),
    }

    def _flag(
        self,
        module: Module,
        node: ast.AST,
        symbol: str,
        kind: str,
        pragmas: Dict[int, str],
        out: List[Finding],
    ) -> None:
        line = getattr(node, "lineno", 1)
        if line in pragmas:
            return
        out.append(
            Finding(self.rule, module.relpath, line, symbol,
                    self._MESSAGES[kind])
        )

    def _scan_iterables(
        self,
        module: Module,
        root: ast.AST,
        symbol: str,
        facts: Dict[str, str],
        dict_sinks: bool,
        pragmas: Dict[int, str],
        out: List[Finding],
    ) -> None:
        """Flag unordered iterates under ``root`` (no nested defs)."""
        # the root itself may be the function whose body we're scanning —
        # the nested-def guard below must only prune defs BELOW it
        if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack = list(ast.iter_child_nodes(root))
        else:
            stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and module.resolve_alias(name) == "sorted":
                    # everything under sorted(...) is order-pinned at
                    # this level — an unordered iterate inside is fine
                    continue
            iters: List[ast.AST] = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters = [node.iter]
            elif isinstance(
                node,
                (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp),
            ):
                iters = [g.iter for g in node.generators]
            for it in iters:
                kind = self._classify(module, it, facts)
                if kind in ("set", "listing"):
                    self._flag(module, it, symbol, kind, pragmas, out)
                elif kind == "dict" and dict_sinks:
                    self._flag(module, it, symbol, kind, pragmas, out)
            if isinstance(node, ast.Assign):
                self._bind_facts(module, node, facts)
            stack.extend(ast.iter_child_nodes(node))

    def _scan_fn(
        self,
        module: Module,
        fn: FunctionInfo,
        pragmas: Dict[int, str],
        out: List[Finding],
    ) -> None:
        facts: Dict[str, str] = {}
        # facts need statement order; the stack walk above visits in
        # reverse, so pre-seed facts with a linear pass first
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                self._bind_facts(module, node, facts)
            elif isinstance(node, ast.Call):
                # names.sort() pins a listing in place
                name = call_name(node)
                if name.endswith(".sort") and "." in name:
                    facts.pop(name.rsplit(".", 1)[0], None)
        self._scan_iterables(
            module,
            fn.node,
            fn.qualname,
            facts,
            self._is_order_sink(module, fn),
            pragmas,
            out,
        )

    def _scan_module_level(
        self, module: Module, pragmas: Dict[int, str], out: List[Finding]
    ) -> None:
        facts: Dict[str, str] = {}
        for stmt in module.tree.body:
            if isinstance(stmt, ast.Assign):
                self._bind_facts(module, stmt, facts)
        for stmt in module.tree.body:
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            self._scan_iterables(
                module, stmt, "<module>", facts, False, pragmas, out
            )
