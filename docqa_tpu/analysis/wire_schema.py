"""wire-schema: every HTTP route serves a LEDGERED response contract.

The reference system's weakest seam was its untyped inter-service wire
(SURVEY.md §1) — and this reproduction re-grew it: ~20 ``/api/*``
endpoints built from hand-rolled dicts in ``service/app.py``, read
positionally by bench/soak/chaos/perf-gate scripts.  ``api_contract.json``
is the one reviewed file that names every endpoint's response key tree,
with a per-endpoint ``version`` an amendment must bump.  This rule holds
the tree to it:

1. **undeclared route** — every ``web.get/post/delete/...`` route
   registration must have a contract entry keyed ``"METHOD /path"``;
   the entry's ``handler`` must name the registered handler and its
   ``version`` must be a positive int.  ``TODO`` anywhere in an entry
   is a finding — the ledger is reviewed, never scaffolded.
2. **stale entry** — a contract entry whose route no longer exists
   fails (PR-3 ledger style).  Staleness only fires on a package that
   actually registers routes, so the ``scripts/`` pass doesn't report
   the whole contract stale.
3. **key drift** — where a handler's payload is DERIVABLE from the AST
   (dict-literal ``json_response`` payloads, ``payload["k"] = ...``
   stores, ``payload.update({...})``), every produced key must be
   declared (NEW keys fail), and for a closed entry with a complete
   derivation every required declared key must be produced (REMOVED
   keys fail).  Call-built payloads (``snapshot()`` returns) derive no
   facts — the live audit (``analysis/wire_audit.py``) covers them.
4. **journal drift** — ``_journal_write(queue, {...})`` record literals
   are held to the contract's ``journal_record`` schema: undeclared
   keys and missing required keys both fail.
5. **model reconciliation** — a contract entry naming a pydantic
   ``model`` must match it exactly (model fields ⊇ required keys,
   ⊆ declared keys); a response model in ``service/schemas.py``
   referenced by neither the contract nor any code is dead and flags.

Spec grammar (shared with wire-consumer and the Tier-B audit): leaves
are JSON type names (``"str" | "int" | "float" | "number" | "bool" |
"any" | "null"``, unions via ``"str|null"``); ``[spec]`` is a list of
``spec``; a dict maps literal keys to specs — a trailing ``?`` marks an
optional key, ``"*"`` declares an open map (arbitrary extra keys).  An
entry with ``"open": true`` requires its declared keys but tolerates
extras (delegated snapshot payloads); closed entries are exact.
Non-JSON surfaces carry ``"kind"`` (``html``/``prometheus-text``/
``sse``) instead of a response tree.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)

LEDGER_NAME = "api_contract.json"

_HTTP_VERBS = {
    "get": "GET",
    "post": "POST",
    "put": "PUT",
    "delete": "DELETE",
    "patch": "PATCH",
}


def default_ledger_path() -> str:
    """The checked-in contract: ``<repo>/api_contract.json``."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg_dir), LEDGER_NAME)


def package_ledger_path(package: Package) -> Optional[str]:
    """Contract next to the analyzed package's root (fixture trees carry
    their own or none; the real runs resolve to the repo's)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            cand = os.path.join(os.path.dirname(base), LEDGER_NAME)
            if os.path.exists(cand):
                return cand
            cand = os.path.join(base, LEDGER_NAME)
            if os.path.exists(cand):
                return cand
    return None


def sibling_path(package: Package, name: str) -> Optional[str]:
    """A repo-root file resolved the same way the contract is (fixture
    trees may carry their own ``bench.py`` / ``perf_baseline.json``)."""
    for module in package.modules:
        rel = module.relpath.replace("/", os.sep)
        if module.path.endswith(rel):
            base = module.path[: -len(rel)].rstrip(os.sep)
            for root in (os.path.dirname(base), base):
                cand = os.path.join(root, name)
                if os.path.exists(cand):
                    return cand
    return None


def load_contract(path: Optional[str]) -> Dict[str, Any]:
    if not path or not os.path.exists(path):
        return {"endpoints": {}}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    data.setdefault("endpoints", {})
    return data


def resolve_contract_path(
    package: Package, override: Optional[str] = None
) -> str:
    return (
        override or package_ledger_path(package) or default_ledger_path()
    )


# ---------------------------------------------------------------------------
# spec-tree helpers (shared with wire_consumer / wire_audit)
# ---------------------------------------------------------------------------


def spec_dict_keys(spec: Dict[str, Any]) -> Tuple[Set[str], Set[str], bool]:
    """(required, all declared, has "*") for a dict spec — declared names
    have the optional ``?`` stripped."""
    required: Set[str] = set()
    declared: Set[str] = set()
    star = False
    for key in spec:
        if key == "*":
            star = True
            continue
        if key.endswith("?"):
            declared.add(key[:-1])
        else:
            declared.add(key)
            required.add(key)
    return required, declared, star


def spec_child(spec: Dict[str, Any], key: str) -> Optional[Any]:
    """The declared sub-spec for ``key`` in a dict spec (``None`` when
    the key is undeclared and the dict has no ``"*"``)."""
    if key in spec:
        return spec[key]
    if key + "?" in spec:
        return spec[key + "?"]
    if "*" in spec:
        return spec["*"]
    return None


def response_dict(entry: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The entry's checkable dict spec: the response tree itself, or the
    element spec of a list-of-dicts response."""
    resp = entry.get("response")
    if isinstance(resp, dict):
        return resp
    if (
        isinstance(resp, list)
        and len(resp) == 1
        and isinstance(resp[0], dict)
    ):
        return resp[0]
    return None


# ---------------------------------------------------------------------------
# route table
# ---------------------------------------------------------------------------


class Route:
    __slots__ = ("method", "path", "handler", "module", "lineno")

    def __init__(self, method, path, handler, module, lineno):
        self.method = method
        self.path = path
        self.handler = handler
        self.module = module
        self.lineno = lineno

    @property
    def key(self) -> str:
        return f"{self.method} {self.path}"


def route_table(package: Package) -> List[Route]:
    """Every ``web.get("/path", handler)``-style registration in the
    package.  The receiver must be (an alias of) ``aiohttp.web`` or a
    bare ``web`` name — ``requests.get(url)`` never parses as a route."""
    routes: List[Route] = []
    for module in package.modules:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            method = _HTTP_VERBS.get(func.attr)
            if method is None or len(node.args) < 2:
                continue
            recv = dotted_name(func.value)
            resolved = module.resolve_alias(recv) if recv else ""
            if recv != "web" and not resolved.startswith("aiohttp"):
                continue
            path_node, handler_node = node.args[0], node.args[1]
            if not (
                isinstance(path_node, ast.Constant)
                and isinstance(path_node.value, str)
            ):
                continue
            handler = dotted_name(handler_node).rsplit(".", 1)[-1]
            if not handler:
                continue
            routes.append(
                Route(
                    method, path_node.value, handler, module, node.lineno
                )
            )
    return routes


# ---------------------------------------------------------------------------
# payload derivation
# ---------------------------------------------------------------------------


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dict_keys_nested(node: ast.Dict) -> Dict[str, Any]:
    """Literal top-level keys; nested dict literals keep their key sets,
    everything else derives no sub-facts (None)."""
    out: Dict[str, Any] = {}
    for k, v in zip(node.keys, node.values):
        key = _const_str(k) if k is not None else None
        if key is None:
            continue
        out[key] = _dict_keys_nested(v) if isinstance(v, ast.Dict) else None
    return out


def payload_facts(
    fn: FunctionInfo,
) -> Tuple[Dict[str, Any], bool, bool, Dict[str, int]]:
    """(produced keys, derivation complete, any json_response site seen,
    key -> lineno anchors) for a route handler.

    Facts come from dict-literal ``json_response`` payloads, local
    ``var = {...}`` dicts later passed, ``var["k"] = ...`` stores, and
    ``var.update({...})``.  A payload that is a call (or a var assigned
    from one) derives nothing and marks the derivation incomplete —
    exactness is then the live audit's job, never a guess here.
    """
    local_dicts: Dict[str, ast.Dict] = {}
    local_calls: Set[str] = set()
    sub_stores: Dict[str, Dict[str, int]] = {}
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if isinstance(node.value, ast.Dict):
                    local_dicts[tgt.id] = node.value
                    local_calls.discard(tgt.id)
                else:
                    local_calls.add(tgt.id)
                    local_dicts.pop(tgt.id, None)
            elif isinstance(tgt, ast.Subscript) and isinstance(
                tgt.value, ast.Name
            ):
                key = _const_str(tgt.slice)
                if key is not None:
                    sub_stores.setdefault(tgt.value.id, {})[key] = (
                        node.lineno
                    )
        elif isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr == "update"
                and isinstance(func.value, ast.Name)
                and node.args
                and isinstance(node.args[0], ast.Dict)
            ):
                for k in node.args[0].keys:
                    key = _const_str(k) if k is not None else None
                    if key is not None:
                        sub_stores.setdefault(func.value.id, {})[key] = (
                            node.lineno
                        )

    produced: Dict[str, Any] = {}
    anchors: Dict[str, int] = {}
    complete = True
    saw_site = False

    def merge(keys: Dict[str, Any], lineno: int) -> None:
        for k, sub in keys.items():
            if k not in produced or produced[k] is None:
                produced[k] = sub
            anchors.setdefault(k, lineno)

    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node).rsplit(".", 1)[-1] != "json_response":
            continue
        # error-status sites carry the {"detail"} error shape, not the
        # endpoint's 200 contract
        status_kw = next(
            (kw.value for kw in node.keywords if kw.arg == "status"), None
        )
        if (
            isinstance(status_kw, ast.Constant)
            and status_kw.value != 200
        ):
            continue
        if not node.args:
            continue
        saw_site = True
        payload = node.args[0]
        if isinstance(payload, ast.Dict):
            merge(_dict_keys_nested(payload), node.lineno)
        elif isinstance(payload, ast.Name):
            name = payload.id
            if name in local_dicts:
                merge(_dict_keys_nested(local_dicts[name]), node.lineno)
            else:
                complete = False
            for k, ln in sub_stores.get(name, {}).items():
                merge({k: None}, ln)
        else:
            complete = False
    return produced, complete, saw_site, anchors


# ---------------------------------------------------------------------------
# pydantic models (service/schemas.py reconciliation)
# ---------------------------------------------------------------------------


def collect_models(
    package: Package,
) -> Dict[str, Tuple[Dict[str, bool], str, int, str]]:
    """Pydantic models in ``*schemas*`` modules:
    name -> (field -> has_default, module relpath, lineno, module name)."""
    models: Dict[str, Tuple[Dict[str, bool], str, int, str]] = {}
    bases_of: Dict[str, List[str]] = {}
    nodes: Dict[str, Tuple[ast.ClassDef, Any]] = {}
    for module in package.modules:
        if "schemas" not in module.name.rsplit(".", 1)[-1]:
            continue
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                bases_of[node.name] = [
                    dotted_name(b).rsplit(".", 1)[-1]
                    for b in node.bases
                    if dotted_name(b)
                ]
                nodes[node.name] = (node, module)

    def is_model(name: str, seen=()) -> bool:
        for b in bases_of.get(name, []):
            if b == "BaseModel":
                return True
            if b in bases_of and b not in seen and is_model(
                b, seen + (name,)
            ):
                return True
        return False

    for name, (node, module) in nodes.items():
        if not is_model(name):
            continue
        fields: Dict[str, bool] = {}
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                fields[stmt.target.id] = stmt.value is not None
        models[name] = (fields, module.relpath, node.lineno, module.name)
    return models


def _referenced_names(package: Package) -> Set[str]:
    """Every Name id / Attribute tail used anywhere in the package,
    class-definition bindings excluded."""
    used: Set[str] = set()
    for module in package.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                used.add(node.attr)
    return used


# ---------------------------------------------------------------------------
# checker
# ---------------------------------------------------------------------------


class WireSchemaChecker:
    rule = "wire-schema"

    def __init__(self, ledger_path: Optional[str] = None):
        self._ledger_path = ledger_path

    def check(self, package: Package) -> List[Finding]:
        path = resolve_contract_path(package, self._ledger_path)
        contract = load_contract(path)
        endpoints: Dict[str, Any] = contract.get("endpoints", {})
        out: List[Finding] = []
        routes = route_table(package)
        if routes:
            out.extend(self._check_routes(package, routes, endpoints))
            out.extend(self._check_stale(routes, endpoints))
            out.extend(self._check_models(package, endpoints))
        out.extend(self._check_journal(package, contract))
        return out

    # -- routes vs entries ----------------------------------------------------

    def _check_routes(
        self,
        package: Package,
        routes: List[Route],
        endpoints: Dict[str, Any],
    ) -> List[Finding]:
        out: List[Finding] = []
        for route in routes:
            entry = endpoints.get(route.key)
            if entry is None:
                out.append(
                    Finding(
                        self.rule,
                        route.module.relpath,
                        route.lineno,
                        route.handler,
                        f"route {route.key} is not declared in "
                        f"{LEDGER_NAME} — add a versioned entry",
                    )
                )
                continue
            if entry.get("handler") != route.handler:
                out.append(
                    Finding(
                        self.rule,
                        route.module.relpath,
                        route.lineno,
                        route.handler,
                        f"{LEDGER_NAME} entry for {route.key} names "
                        f"handler '{entry.get('handler')}' but the route "
                        f"registers '{route.handler}'",
                    )
                )
            version = entry.get("version")
            if not isinstance(version, int) or version < 1:
                out.append(
                    Finding(
                        self.rule,
                        route.module.relpath,
                        route.lineno,
                        route.handler,
                        f"{LEDGER_NAME} entry for {route.key} needs a "
                        "positive integer 'version'",
                    )
                )
            if "TODO" in json.dumps(entry):
                out.append(
                    Finding(
                        self.rule,
                        route.module.relpath,
                        route.lineno,
                        route.handler,
                        f"{LEDGER_NAME} entry for {route.key} carries a "
                        "TODO — the contract is reviewed, not scaffolded",
                    )
                )
            out.extend(self._check_payload(package, route, entry))
        return out

    def _handler_fn(
        self, package: Package, route: Route
    ) -> Optional[FunctionInfo]:
        cands = [
            fn
            for fn in package.functions
            if fn.name == route.handler and fn.module is route.module
        ]
        if len(cands) == 1:
            return cands[0]
        return None  # missing or ambiguous: never guess

    def _check_payload(
        self, package: Package, route: Route, entry: Dict[str, Any]
    ) -> List[Finding]:
        spec = response_dict(entry)
        if spec is None:
            return []
        fn = self._handler_fn(package, route)
        if fn is None:
            return []
        produced, complete, saw_site, anchors = payload_facts(fn)
        if not saw_site:
            return []
        out: List[Finding] = []
        required, declared, star = spec_dict_keys(spec)
        for key, sub in sorted(produced.items()):
            line = anchors.get(key, fn.node.lineno)
            if fn.module.is_suppressed(self.rule, line):
                continue
            if key not in declared and not star:
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        line,
                        fn.qualname,
                        f"handler produces key '{key}' not declared for "
                        f"{route.key} in {LEDGER_NAME} — declare it and "
                        "bump the entry's version",
                    )
                )
                continue
            child = spec_child(spec, key)
            if isinstance(sub, dict) and isinstance(child, dict):
                c_req, c_decl, c_star = spec_dict_keys(child)
                for sk in sorted(sub):
                    if sk not in c_decl and not c_star:
                        out.append(
                            Finding(
                                self.rule,
                                fn.module.relpath,
                                line,
                                fn.qualname,
                                f"handler produces key '{key}.{sk}' not "
                                f"declared for {route.key} in "
                                f"{LEDGER_NAME}",
                            )
                        )
        if complete and not entry.get("open"):
            for key in sorted(required - set(produced)):
                if fn.module.is_suppressed(self.rule, fn.node.lineno):
                    continue
                out.append(
                    Finding(
                        self.rule,
                        fn.module.relpath,
                        fn.node.lineno,
                        fn.qualname,
                        f"{LEDGER_NAME} declares key '{key}' for "
                        f"{route.key} but the handler never produces it "
                        "— remove it from the contract (version bump) or "
                        "restore the field",
                    )
                )
        return out

    def _check_stale(
        self, routes: List[Route], endpoints: Dict[str, Any]
    ) -> List[Finding]:
        route_keys = {r.key for r in routes}
        anchor = routes[0].module
        out: List[Finding] = []
        for key in sorted(endpoints):
            if key not in route_keys:
                out.append(
                    Finding(
                        self.rule,
                        anchor.relpath,
                        1,
                        "<ledger>",
                        f"stale {LEDGER_NAME} entry: no route registers "
                        f"{key}",
                    )
                )
        return out

    # -- journal records ------------------------------------------------------

    def _check_journal(
        self, package: Package, contract: Dict[str, Any]
    ) -> List[Finding]:
        spec = contract.get("journal_record")
        if not isinstance(spec, dict):
            return []
        required, declared, star = spec_dict_keys(spec)
        out: List[Finding] = []
        for fn in package.functions:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                if call_name(node).rsplit(".", 1)[-1] != "_journal_write":
                    continue
                if len(node.args) < 2 or not isinstance(
                    node.args[1], ast.Dict
                ):
                    continue
                if fn.module.is_suppressed(self.rule, node.lineno):
                    continue
                keys = set(_dict_keys_nested(node.args[1]))
                for key in sorted(keys - declared):
                    if star:
                        break
                    out.append(
                        Finding(
                            self.rule,
                            fn.module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"journal record key '{key}' is not declared "
                            f"in {LEDGER_NAME} journal_record",
                        )
                    )
                for key in sorted(required - keys):
                    out.append(
                        Finding(
                            self.rule,
                            fn.module.relpath,
                            node.lineno,
                            fn.qualname,
                            f"journal record is missing required key "
                            f"'{key}' ({LEDGER_NAME} journal_record)",
                        )
                    )
        return out

    # -- pydantic model reconciliation ---------------------------------------

    def _check_models(
        self, package: Package, endpoints: Dict[str, Any]
    ) -> List[Finding]:
        models = collect_models(package)
        if not models:
            return []
        out: List[Finding] = []
        referenced_by_contract: Set[str] = set()
        for key, entry in sorted(endpoints.items()):
            model_name = entry.get("model")
            if not model_name:
                continue
            referenced_by_contract.add(model_name)
            model = models.get(model_name)
            if model is None:
                # anchor at the schemas module if one exists in-package
                relpath, lineno = next(
                    ((m[1], 1) for m in models.values()), (None, 1)
                )
                if relpath is not None:
                    out.append(
                        Finding(
                            self.rule,
                            relpath,
                            lineno,
                            "<ledger>",
                            f"{LEDGER_NAME} entry for {key} names model "
                            f"'{model_name}' which is not defined in the "
                            "schemas module",
                        )
                    )
                continue
            spec = response_dict(entry)
            if spec is None:
                continue
            fields, relpath, lineno, _mod = model
            required, declared, star = spec_dict_keys(spec)
            if package.modules and any(
                m.relpath == relpath
                and m.is_suppressed(self.rule, lineno)
                for m in package.modules
            ):
                continue
            missing = sorted(required - set(fields))
            extra = sorted(set(fields) - declared) if not star else []
            if missing or extra:
                bits = []
                if missing:
                    bits.append(f"missing contract keys {missing}")
                if extra:
                    bits.append(f"undeclared fields {extra}")
                out.append(
                    Finding(
                        self.rule,
                        relpath,
                        lineno,
                        model_name,
                        f"pydantic model {model_name} drifted from the "
                        f"{LEDGER_NAME} entry for {key}: "
                        + "; ".join(bits),
                    )
                )
        # transitive closure: models nested in referenced models stay live
        used = _referenced_names(package)
        for name, (fields, relpath, lineno, _mod) in sorted(
            models.items()
        ):
            if name in referenced_by_contract:
                continue
            if name in used:
                continue
            module = next(
                (m for m in package.modules if m.relpath == relpath), None
            )
            if module is not None and module.is_suppressed(
                self.rule, lineno
            ):
                continue
            out.append(
                Finding(
                    self.rule,
                    relpath,
                    lineno,
                    name,
                    f"dead schema model {name}: referenced by no code "
                    f"and no {LEDGER_NAME} entry",
                )
            )
        return out
