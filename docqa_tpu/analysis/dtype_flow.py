"""dtype-flow: low-precision math must accumulate wide, and nothing may
silently widen a bf16 pipeline.

The serving stack stores weights in bf16/int8/int4 (``models/quant.py``)
because decode is HBM-bandwidth bound — but the MATH contract is that
every matmul over those operands accumulates in float32
(``preferred_element_type``), every reduction over bf16 activations
upcasts first, and nothing drags float64 (TPU-emulated, 2x bytes) into
device code.  Until now that contract lived in comments
(``index/ivf.py:156``: "All scores accumulate to f32"); this checker
makes it a red build.

Dtype **facts** are tracked per name, per function, flow-insensitively in
statement order — no type inference, only what the source states:

* literal dtype references through import aliases (``jnp.bfloat16``,
  ``np.int8``, ``ml_dtypes.int4``, ``"bfloat16"`` strings,
  ``jnp.dtype("bfloat16")``);
* ``x = y.astype(D)`` rebinds ``x`` to ``D``'s fact — including the
  ``.dtype`` rebind form ``y.astype(z.dtype)`` (``x`` takes ``z``'s
  fact, the idiom ``serve._prefill_program`` uses);
* array creation (``jnp.zeros/ones/full/empty/asarray/array``,
  ``jax.ShapeDtypeStruct``) with a resolvable dtype argument;
* propagation through ``.T``/subscripts/unary ops/binary ops (Python
  scalar literals are weak-typed and never widen a fact);
* cross-module: a call that resolves through the package index
  (:meth:`~docqa_tpu.analysis.core.Package.resolve_call`) re-scans the
  callee with the caller's low-precision argument facts bound to its
  parameters (depth-limited, memoized), and a resolved callee's RETURN
  fact flows back — so the int8/int4 tensors minted at the quant
  boundary (``models/quant.py:quantize_array`` returns are ``.astype(
  jnp.int8)``) stay tracked through helper layers.

Findings (ambiguity never guesses — an unresolvable dtype is silent):

1. ``@`` / ``jnp.dot`` / ``jnp.matmul`` / ``jnp.einsum`` /
   ``jnp.tensordot`` / ``lax.dot_general`` with a bf16/f16/int8/int4
   operand fact and no ``preferred_element_type`` of f32-or-wider;
2. reductions over bf16/f16 facts — ``sum``/``mean``/``var``/``std``/
   ``prod``/``logsumexp`` (function or method form) without a wide
   ``dtype=``, and ``softmax``/``log_softmax`` (no accumulator kwarg
   exists — the operand itself must be upcast first);
3. float64 entering device code: an f64 dtype argument to any ``jnp``/
   ``jax`` call, or ``.astype(float64)`` on a value with a known float
   fact;
4. silent widening: a binary op between a bf16/f16 fact and an f64 fact
   (the weak-type promotion that turns a bf16 pipeline f64 without any
   visible cast).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)

# canonical category names; width order for promotion
_DTYPE_NAMES = {
    "int4": "i4",
    "int8": "i8",
    "uint8": "i8",
    "bfloat16": "bf16",
    "float16": "f16",
    "half": "f16",
    "int32": "i32",
    "int64": "i64",
    "float32": "f32",
    "single": "f32",
    "float64": "f64",
    "double": "f64",
}
_WIDTH = {"i4": 0, "i8": 1, "bf16": 2, "f16": 2, "i32": 3, "i64": 4,
          "f32": 5, "f64": 6}
LOW_MATMUL = frozenset({"bf16", "f16", "i8", "i4"})
LOW_FLOAT = frozenset({"bf16", "f16"})
WIDE_ACC = frozenset({"f32", "f64", "i32", "i64"})

# heads whose attributes are dtype namespaces (post alias resolution)
_DTYPE_HEADS = ("jax.numpy", "jax", "numpy", "jnp", "np", "ml_dtypes")

_MATMUL_TAILS = frozenset({"dot", "matmul", "einsum", "tensordot",
                           "dot_general"})
_REDUCE_TAILS = frozenset({"sum", "mean", "var", "std", "prod",
                           "logsumexp"})
_SOFTMAX_TAILS = frozenset({"softmax", "log_softmax"})
_CREATE_TAILS = {
    # tail -> positional index of the dtype argument (after the first)
    "zeros": 1, "ones": 1, "empty": 1, "full": 2,
    "asarray": 1, "array": 1, "full_like": 2, "arange": None,
}

_MAX_DEPTH = 5


def _is_jnp_head(resolved: str) -> bool:
    head = resolved.split(".")[0]
    return head in ("jax", "jnp") or resolved.startswith("jax.")


class DtypeFlowChecker:
    rule = "dtype-flow"

    def check(self, package: Package) -> List[Finding]:
        self._package = package
        self._out: List[Finding] = []
        self._seen: set = set()  # (node id, fact context) scan memo
        self._ret_memo: Dict[int, object] = {}
        for fn in package.functions:
            self._scan(fn, {}, via="", depth=0)
        for module in package.modules:
            pseudo = FunctionInfo(
                module=module, node=module.tree, qualname="<module>",
                class_name=None,
            )
            self._scan(pseudo, {}, via="", depth=0)
        return self._out

    # -- dtype literal resolution -------------------------------------------

    def _dtype_of(self, module, node: Optional[ast.AST],
                  facts: Dict[str, Optional[str]]) -> Optional[str]:
        """Category of an expression used IN DTYPE POSITION, or None."""
        if node is None:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return _DTYPE_NAMES.get(node.value)
        if isinstance(node, (ast.Name, ast.Attribute)):
            dotted = dotted_name(node)
            if isinstance(node, ast.Attribute) and node.attr == "dtype":
                # y.dtype in dtype position: the .dtype rebind — take y's fact
                return self._fact_quiet(module, node.value, facts)
            resolved = module.resolve_alias(dotted)
            tail = resolved.rsplit(".", 1)[-1]
            cat = _DTYPE_NAMES.get(tail)
            if cat is None:
                return None
            if "." not in resolved:
                return cat  # from-import of the dtype name itself
            head = resolved.rsplit(".", 1)[0]
            return cat if head in _DTYPE_HEADS or head.startswith("jax") else None
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name.rsplit(".", 1)[-1] == "dtype" and node.args:
                return self._dtype_of(module, node.args[0], facts)
        return None

    def _fact_quiet(self, module, node, facts):
        """Fact of an expression without emitting findings (used from
        dtype-position resolution, where nothing is computed)."""
        sink: List[Finding] = []
        return self._eval(None, module, node, facts, sink, depth=_MAX_DEPTH)

    # -- function scan -------------------------------------------------------

    def _scan(self, fn: FunctionInfo, param_facts: Dict[str, Optional[str]],
              via: str, depth: int) -> None:
        key = (id(fn.node), tuple(sorted(
            (k, v) for k, v in param_facts.items() if v
        )))
        if key in self._seen or depth > _MAX_DEPTH:
            return
        self._seen.add(key)
        facts: Dict[str, Optional[str]] = dict(param_facts)
        body = getattr(fn.node, "body", None)
        if body is None:
            return
        self._exec_block(fn, body, facts, via, depth)

    def _exec_block(self, fn, stmts, facts, via, depth) -> None:
        for stmt in stmts:
            self._exec_stmt(fn, stmt, facts, via, depth)

    def _exec_stmt(self, fn, stmt, facts, via, depth) -> None:
        module = fn.module
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # own FunctionInfo pass
        if isinstance(stmt, ast.Assign):
            fact = self._eval(fn, module, stmt.value, facts, self._out,
                              depth, via=via)
            for target in stmt.targets:
                self._bind(target, fact, facts)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            fact = self._eval(fn, module, stmt.value, facts, self._out,
                              depth, via=via)
            self._bind(stmt.target, fact, facts)
            return
        if isinstance(stmt, ast.AugAssign):
            self._eval(fn, module, stmt.value, facts, self._out, depth,
                       via=via)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._eval(fn, module, stmt.value, facts, self._out, depth,
                           via=via)
            return
        if isinstance(stmt, ast.Expr):
            self._eval(fn, module, stmt.value, facts, self._out, depth,
                       via=via)
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            for attr in ("iter", "test"):
                sub = getattr(stmt, attr, None)
                if sub is not None:
                    self._eval(fn, module, sub, facts, self._out, depth,
                               via=via)
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._bind(stmt.target, None, facts)
            self._exec_block(fn, stmt.body, facts, via, depth)
            self._exec_block(fn, stmt.orelse, facts, via, depth)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(fn, module, item.context_expr, facts, self._out,
                           depth, via=via)
            self._exec_block(fn, stmt.body, facts, via, depth)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(fn, stmt.body, facts, via, depth)
            for handler in stmt.handlers:
                self._exec_block(fn, handler.body, facts, via, depth)
            self._exec_block(fn, stmt.orelse, facts, via, depth)
            self._exec_block(fn, stmt.finalbody, facts, via, depth)
            return
        # any other statement kind: evaluate nested expressions for findings
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._eval(fn, module, sub, facts, self._out, depth, via=via)

    @staticmethod
    def _bind(target, fact, facts) -> None:
        if isinstance(target, ast.Name):
            facts[target.id] = fact if isinstance(fact, str) else None
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            sub = fact if isinstance(fact, tuple) else (None,) * len(elts)
            if len(sub) != len(elts):
                sub = (None,) * len(elts)
            for t, f in zip(elts, sub):
                DtypeFlowChecker._bind(t, f, facts)

    # -- expression evaluation (facts + findings) ----------------------------

    def _add(self, fn, node, message, via) -> None:
        suffix = f" [dtype via {via}]" if via else ""
        self._out.append(
            Finding(
                self.rule,
                fn.module.relpath,
                getattr(node, "lineno", 1),
                fn.qualname,
                message + suffix,
            )
        )

    def _eval(self, fn, module, node, facts, out, depth, via=""):
        """Returns the fact (category str, tuple of facts, or None) and
        appends findings for the patterns in the module docstring.  ``fn``
        may be None for quiet dtype-position evaluation."""
        if isinstance(node, ast.Name):
            return facts.get(node.id)
        if isinstance(node, ast.Attribute):
            if node.attr in ("T", "mT", "real", "imag"):
                return self._eval(fn, module, node.value, facts, out, depth,
                                  via)
            return None
        if isinstance(node, ast.Subscript):
            self._eval(fn, module, node.slice, facts, out, depth, via)
            return self._eval(fn, module, node.value, facts, out, depth, via)
        if isinstance(node, ast.UnaryOp):
            return self._eval(fn, module, node.operand, facts, out, depth,
                              via)
        if isinstance(node, ast.Tuple):
            return tuple(
                self._eval(fn, module, e, facts, out, depth, via)
                for e in node.elts
            )
        if isinstance(node, ast.Lambda):
            inner = dict(facts)
            for a in node.args.args:
                inner[a.arg] = None
            return self._eval(fn, module, node.body, inner, out, depth, via)
        if isinstance(node, ast.IfExp):
            self._eval(fn, module, node.test, facts, out, depth, via)
            a = self._eval(fn, module, node.body, facts, out, depth, via)
            b = self._eval(fn, module, node.orelse, facts, out, depth, via)
            return a if a == b else None
        if isinstance(node, ast.BinOp):
            left = self._eval(fn, module, node.left, facts, out, depth, via)
            right = self._eval(fn, module, node.right, facts, out, depth, via)
            lf = left if isinstance(left, str) else None
            rf = right if isinstance(right, str) else None
            if isinstance(node.op, ast.MatMult):
                if fn is not None and (lf in LOW_MATMUL or rf in LOW_MATMUL):
                    low = lf if lf in LOW_MATMUL else rf
                    self._add(
                        fn, node,
                        f"{low} matmul via '@' without f32 accumulation "
                        f"(use jnp.matmul/lax.dot_general with "
                        f"preferred_element_type=jnp.float32)",
                        via,
                    )
                return self._widest(lf, rf)
            if fn is not None and (
                (lf in LOW_FLOAT and rf == "f64")
                or (rf in LOW_FLOAT and lf == "f64")
            ):
                self._add(
                    fn, node,
                    "float64 operand silently widens a bf16/f16 pipeline "
                    "(weak-type promotion; cast explicitly or keep f32)",
                    via,
                )
            return self._widest(lf, rf)
        if isinstance(node, ast.Call):
            return self._eval_call(fn, module, node, facts, out, depth, via)
        if isinstance(node, (ast.List, ast.Set)):
            for e in node.elts:
                self._eval(fn, module, e, facts, out, depth, via)
            return None
        if isinstance(node, ast.Dict):
            for e in list(node.keys) + list(node.values):
                if e is not None:
                    self._eval(fn, module, e, facts, out, depth, via)
            return None
        if isinstance(node, ast.Compare):
            self._eval(fn, module, node.left, facts, out, depth, via)
            for c in node.comparators:
                self._eval(fn, module, c, facts, out, depth, via)
            return None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return None  # comprehension scopes: out of fact range
        return None

    @staticmethod
    def _widest(a: Optional[str], b: Optional[str]) -> Optional[str]:
        if a is None:
            return b
        if b is None:
            return a
        return a if _WIDTH.get(a, 0) >= _WIDTH.get(b, 0) else b

    def _kwarg(self, node: ast.Call, name: str) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _eval_call(self, fn, module, node, facts, out, depth, via):
        name = call_name(node)
        resolved = module.resolve_alias(name) if name else ""
        tail = name.rsplit(".", 1)[-1] if name else ""
        if not isinstance(node.func, (ast.Name, ast.Attribute)):
            # computed target — e.g. jax.jit(lambda ...)(args): the
            # wrapper call (and any lambda body) still carries dtype flow
            self._eval(fn, module, node.func, facts, out, depth, via)
        arg_facts = [
            self._eval(fn, module, a, facts, out, depth, via)
            for a in node.args
        ]
        for kw in node.keywords:
            self._eval(fn, module, kw.value, facts, out, depth, via)

        # float64 entering a jax/jnp call through any dtype-ish argument
        if fn is not None and _is_jnp_head(resolved):
            for candidate in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                if self._dtype_of(module, candidate, facts) == "f64":
                    self._add(
                        fn, node,
                        f"float64 dtype passed to {name}() — f64 is "
                        "TPU-emulated and doubles HBM traffic; use float32",
                        via,
                    )
                    break

        # x.astype(D): the rebind
        if tail == "astype" and isinstance(node.func, ast.Attribute):
            recv = self._eval(fn, module, node.func.value, facts, out,
                              depth, via)
            cat = self._dtype_of(module, node.args[0] if node.args else None,
                                 facts)
            if (
                fn is not None
                and cat == "f64"
                and isinstance(recv, str)
                and recv in ("bf16", "f16", "f32")
            ):
                self._add(
                    fn, node,
                    "astype(float64) on a float pipeline value — f64 is "
                    "TPU-emulated; accumulate in float32 instead",
                    via,
                )
            return cat

        # creation calls with a dtype argument
        head = resolved.split(".")[0]
        if tail in _CREATE_TAILS and head in ("jax", "jnp", "np", "numpy"):
            d = self._kwarg(node, "dtype")
            if d is None:
                pos = _CREATE_TAILS[tail]
                if pos is not None and len(node.args) > pos:
                    d = node.args[pos]
            return self._dtype_of(module, d, facts)
        if tail == "ShapeDtypeStruct" and len(node.args) >= 2:
            return self._dtype_of(module, node.args[1], facts)

        # matmul family
        if tail in _MATMUL_TAILS and (
            _is_jnp_head(resolved) or head in ("np", "numpy")
        ):
            if tail == "einsum" and node.args and isinstance(
                node.args[0], ast.Constant
            ):
                operands = arg_facts[1:]
            elif tail == "dot_general":
                operands = arg_facts[:2]
            else:
                operands = arg_facts[:2]
            pet = self._kwarg(node, "preferred_element_type")
            pet_cat = self._dtype_of(module, pet, facts)
            low = next((f for f in operands if f in LOW_MATMUL), None)
            if fn is not None and low is not None:
                if pet is None:
                    self._add(
                        fn, node,
                        f"{low} operand to {tail}() without "
                        "preferred_element_type — low-precision matmuls "
                        "must accumulate in float32 or wider",
                        via,
                    )
                elif pet_cat is not None and pet_cat not in WIDE_ACC:
                    self._add(
                        fn, node,
                        f"{tail}() accumulates a {low} operand into "
                        f"{pet_cat} — preferred_element_type must be "
                        "float32 or wider",
                        via,
                    )
            if pet_cat is not None:
                return pet_cat
            known = [f for f in operands if isinstance(f, str)]
            return known[0] if len(known) == len(operands) and known else None

        # method-form matmul: x.dot(y)
        if tail == "dot" and isinstance(node.func, ast.Attribute):
            recv = self._eval(fn, module, node.func.value, facts, out,
                              depth, via)
            if fn is not None and (
                recv in LOW_MATMUL
                or any(f in LOW_MATMUL for f in arg_facts)
            ):
                self._add(
                    fn, node,
                    "low-precision .dot() without f32 accumulation (use "
                    "jnp.matmul/lax.dot_general with "
                    "preferred_element_type=jnp.float32)",
                    via,
                )
            return recv if isinstance(recv, str) else None

        # reductions
        if tail in _REDUCE_TAILS:
            operand = None
            if isinstance(node.func, ast.Attribute) and head not in (
                "jnp", "np", "numpy", "jax"
            ):
                operand = self._eval(fn, module, node.func.value, facts,
                                     out, depth, via)
            elif arg_facts:
                if _is_jnp_head(resolved) or head in ("np", "numpy"):
                    operand = arg_facts[0]
            dt = self._dtype_of(module, self._kwarg(node, "dtype"), facts)
            if fn is not None and operand in LOW_FLOAT and (
                dt is None or dt not in WIDE_ACC
            ):
                self._add(
                    fn, node,
                    f"{tail}() reduces a {operand} value without an f32 "
                    "accumulator — pass dtype=jnp.float32 or upcast the "
                    "operand first",
                    via,
                )
            return dt or (operand if isinstance(operand, str) else None)
        if tail in _SOFTMAX_TAILS and arg_facts:
            if fn is not None and arg_facts[0] in LOW_FLOAT:
                self._add(
                    fn, node,
                    f"{tail}() over a {arg_facts[0]} value — softmax "
                    "must run in float32 (upcast the scores first)",
                    via,
                )
            return arg_facts[0] if isinstance(arg_facts[0], str) else None

        # jnp.dtype(...) in value position
        if tail == "dtype" and node.args:
            return self._dtype_of(module, node.args[0], facts)

        # cross-module propagation through the package index
        if fn is not None and self._package is not None:
            callee = self._package.resolve_call(fn, node)
            if callee is not None and hasattr(callee.node, "args"):
                low_binding = self._bind_params(callee, node, arg_facts)
                if low_binding:
                    self._scan(
                        callee, low_binding,
                        via=via or fn.qualname, depth=depth + 1,
                    )
                return self._return_fact(callee, depth + 1)
        return None

    def _bind_params(self, callee: FunctionInfo, node: ast.Call,
                     arg_facts) -> Dict[str, Optional[str]]:
        """Positional/keyword binding of LOW facts onto callee params;
        empty when no low fact crosses the call (nothing new to scan)."""
        params = callee.params
        offset = 1 if callee.class_name and params[:1] == ["self"] else 0
        binding: Dict[str, Optional[str]] = {}
        for i, f in enumerate(arg_facts):
            if f in LOW_MATMUL and i + offset < len(params):
                binding[params[i + offset]] = f
        for kw in node.keywords:
            if kw.arg and kw.arg in params:
                # facts for keywords were evaluated already; re-derive is
                # costlier than it is worth — positional covers the tree
                continue
        return binding

    def _return_fact(self, callee: FunctionInfo, depth: int):
        """Fact of a resolved callee's return value, from a quiet scan of
        its body with no parameter facts (memoized)."""
        if depth > _MAX_DEPTH:
            return None
        memo = self._ret_memo
        key = id(callee.node)
        if key in memo:
            return memo[key]
        memo[key] = None  # cycle guard
        facts: Dict[str, Optional[str]] = {}
        sink: List[Finding] = []
        rets = []

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.Assign):
                    fact = self._eval(None, callee.module, stmt.value, facts,
                                      sink, depth)
                    for t in stmt.targets:
                        self._bind(t, fact, facts)
                elif isinstance(stmt, ast.Return) and stmt.value is not None:
                    rets.append(
                        self._eval(None, callee.module, stmt.value, facts,
                                   sink, depth)
                    )
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list):
                        walk(sub)
                if isinstance(stmt, ast.Try):
                    for handler in stmt.handlers:
                        walk(handler.body)

        body = getattr(callee.node, "body", None)
        if body:
            walk(body)
        uniq = {repr(r) for r in rets}
        result = rets[0] if len(uniq) == 1 and rets else None
        memo[key] = result
        return result
