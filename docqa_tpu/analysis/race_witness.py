"""race-witness: runtime instrumentation of lock acquisition order.

The static acquisition graph (``lock_discipline.build_acquisition_graph``)
is a model; this module records what the process actually DOES.  With the
witness installed, every ``threading.Lock`` / ``RLock`` / ``Condition``
created at a source line the static analyzer knows (the
``self._x = threading.Lock()`` declarations ``concurrency.discover_locks``
enumerates) is wrapped, and each acquisition records:

* **witnessed lock-order edges** — acquiring B while holding A adds edge
  ``A → B`` to the witnessed graph, under the SAME ``Class.attr``
  identity and Condition→lock aliasing the static graph uses, so the two
  views cross-check edge-for-edge;
* **held-lock blocking events** — a ``Condition.wait`` entered while
  OTHER locks are held (waiting releases only the cv's own lock), and
  any acquisition that blocked longer than ``blocking_ms`` while the
  thread held something (measured contention, the precondition of every
  order-inversion deadlock).

The gate (``scripts/chaos_smoke.py``; soak pulls the same dump over
``GET /api/witness``):

* a **cycle** in the witnessed graph fails the run — that is a deadlock
  the chaos load simply didn't lose the coin-flip on;
* a witnessed edge **missing from the static graph** fails the run —
  the analyzer has a blind spot (an unresolvable call, a lock the
  discovery missed) that must be fixed or the edge explicitly waived,
  otherwise the static gate is quietly vouching for orderings it never
  checked.

Known blind spot, by design: primitives created through dataclass
``field(default_factory=…)`` (the per-request ``_Request.cv``) construct
inside generated ``__init__`` code, so their creation site cannot be
mapped back to a declaration — they stay unwrapped, and the static
rules (guarded-state, cv-protocol) carry them instead.

Overhead is a dict update per acquisition on wrapped locks only; the
witness is opt-in (chaos/soak/tests, ``DOCQA_RACE_WITNESS=1`` for a
served process) and never belongs in a latency benchmark.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.concurrency import canonical, find_cycles

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

# stack frames from these files are machinery, not creation sites
_SKIP_FRAME_PARTS = (
    os.sep + "threading.py",
    os.sep + "dataclasses.py",
    "race_witness.py",
)


def build_lock_id_map(
    paths: Optional[List[str]] = None,
) -> Tuple[Dict[Tuple[str, int], str], Dict[str, str], Dict]:
    """(creation-site → lock id, aliases, static edges) for the witness.

    ``paths`` defaults to the installed ``docqa_tpu`` package + the
    repo's ``scripts/`` — the same scope as ``scripts/lint.py``.  The
    creation-site key is ``(absolute source path, factory lineno)``:
    exactly what a stack walk sees when the patched factory runs."""
    from docqa_tpu.analysis.core import Package
    from docqa_tpu.analysis.concurrency import discover_locks, lock_aliases
    from docqa_tpu.analysis.lock_discipline import build_acquisition_graph

    if paths is None:
        pkg_dir = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        paths = [pkg_dir]
        scripts = os.path.join(os.path.dirname(pkg_dir), "scripts")
        if os.path.isdir(scripts):
            paths.append(scripts)
    id_map: Dict[Tuple[str, int], str] = {}
    aliases: Dict[str, str] = {}
    edges: Dict = {}
    for root in paths:
        package = Package.load(root)
        decls = discover_locks(package)
        for decl in decls.values():
            id_map[
                (os.path.abspath(decl.module_abspath), decl.lineno)
            ] = decl.lock_id
        aliases.update(lock_aliases(decls))
        edges.update(build_acquisition_graph(package))
    return id_map, aliases, edges


class _HeldState(threading.local):
    def __init__(self) -> None:
        self.stack: List[str] = []  # canonical ids, acquisition order
        self.counts: Dict[str, int] = {}  # reentrancy


class LockOrderWitness:
    """Records the witnessed acquisition-order graph + blocking events."""

    def __init__(
        self,
        id_map: Dict[Tuple[str, int], str],
        aliases: Optional[Dict[str, str]] = None,
        blocking_ms: float = 50.0,
    ) -> None:
        self.id_map = dict(id_map)
        self.aliases = dict(aliases or {})
        self.blocking_ms = float(blocking_ms)
        self._held = _HeldState()
        self._mu = _REAL_LOCK()  # witness-internal; never wrapped
        # (from, to) -> {"count", "example_thread"}
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.blocking: List[Dict[str, Any]] = []
        self.locks_seen: Set[str] = set()
        self._installed = False

    # ---- recording -----------------------------------------------------------

    def _canon(self, lock_id: str) -> str:
        return canonical(lock_id, self.aliases)

    def on_acquired(self, lock_id: str, waited_s: float) -> None:
        lid = self._canon(lock_id)
        held = self._held
        n = held.counts.get(lid, 0)
        held.counts[lid] = n + 1
        if n:  # reentrant re-acquire: no new node on the stack
            return
        new_edges = []
        for h in held.stack:
            if h != lid:
                new_edges.append((h, lid))
        held.stack.append(lid)
        blocked = waited_s * 1000.0 >= self.blocking_ms and bool(
            held.stack[:-1]
        )
        if not new_edges and not blocked:
            with self._mu:
                self.locks_seen.add(lid)
            return
        tname = threading.current_thread().name
        with self._mu:
            self.locks_seen.add(lid)
            for edge in new_edges:
                row = self.edges.setdefault(
                    edge, {"count": 0, "example_thread": tname}
                )
                row["count"] += 1
            if blocked:
                self.blocking.append(
                    {
                        "op": "acquire",
                        "lock": lid,
                        "held": list(held.stack[:-1]),
                        "ms": round(waited_s * 1000.0, 3),
                        "thread": tname,
                    }
                )

    def on_released(self, lock_id: str) -> None:
        lid = self._canon(lock_id)
        held = self._held
        n = held.counts.get(lid, 0)
        if n > 1:
            held.counts[lid] = n - 1
            return
        held.counts.pop(lid, None)
        if lid in held.stack:
            held.stack.remove(lid)

    def on_cv_wait(self, lock_id: str) -> None:
        """Entering ``Condition.wait``: the cv's own lock is released,
        anything ELSE still held is a held-lock blocking call."""
        lid = self._canon(lock_id)
        others = [h for h in self._held.stack if h != lid]
        if others:
            with self._mu:
                self.blocking.append(
                    {
                        "op": "cv_wait",
                        "lock": lid,
                        "held": others,
                        "thread": threading.current_thread().name,
                    }
                )

    # ---- results -------------------------------------------------------------

    def _edge_keys(self) -> List[Tuple[str, str]]:
        """Stable copy of the edge set — cycles()/cross_check() must
        never iterate the LIVE dict: on_acquired() inserts from any
        thread, and a mid-iteration insert is a RuntimeError exactly
        while /api/witness observes a loaded process."""
        with self._mu:
            return list(self.edges.keys())

    def cycles(self) -> List[List[str]]:
        return find_cycles(self._edge_keys())

    def cross_check(self, static_edges) -> List[Tuple[str, str]]:
        """Witnessed edges absent from the static acquisition graph."""
        static = set(static_edges)
        return sorted(e for e in self._edge_keys() if e not in static)

    def snapshot(
        self, static_edges=None
    ) -> Dict[str, Any]:
        with self._mu:
            edge_items = sorted(self.edges.items())
            edges = [
                {"from": a, "to": b, **row} for (a, b), row in edge_items
            ]
            blocking = list(self.blocking)
            locks = sorted(self.locks_seen)
        edge_keys = [key for key, _row in edge_items]
        out: Dict[str, Any] = {
            "locks_seen": locks,
            "edges": edges,
            "blocking": blocking,
            "cycles": find_cycles(edge_keys),
        }
        if static_edges is not None:
            static = set(static_edges)
            out["static_edge_count"] = len(static)
            out["edges_missing_from_static"] = [
                list(e) for e in edge_keys if e not in static
            ]
        return out

    # ---- installation --------------------------------------------------------

    def _creation_id(self) -> Optional[str]:
        import sys

        frame = sys._getframe(2)
        while frame is not None:
            fname = frame.f_code.co_filename
            if not any(p in fname for p in _SKIP_FRAME_PARTS) and not (
                fname.startswith("<")
            ):
                break
            frame = frame.f_back
        if frame is None:
            return None
        key = (os.path.abspath(frame.f_code.co_filename), frame.f_lineno)
        return self.id_map.get(key)

    def install(self) -> "LockOrderWitness":
        """Patch the threading factories.  Only locks created AFTER this
        (at mapped declaration sites) are wrapped; everything else gets
        the real primitive untouched."""
        if self._installed:
            return self
        self._installed = True
        witness = self

        def make_lock(*a, **kw):
            lid = witness._creation_id()
            inner = _REAL_LOCK(*a, **kw)
            return inner if lid is None else _WitnessLock(
                inner, lid, witness
            )

        def make_rlock(*a, **kw):
            lid = witness._creation_id()
            inner = _REAL_RLOCK(*a, **kw)
            return inner if lid is None else _WitnessLock(
                inner, lid, witness
            )

        def make_condition(lock=None, *a, **kw):
            lid = witness._creation_id()
            inner_lock = lock
            base_id = None
            if isinstance(lock, _WitnessLock):
                inner_lock = lock._inner
                base_id = lock.lock_id
            inner = _REAL_CONDITION(inner_lock, *a, **kw)
            if lid is None:
                return inner
            if base_id is not None:
                # Condition(self._lock): ONE lock, two names — record
                # under the lock's id so the graphs don't grow a
                # self-alias edge
                witness.aliases.setdefault(lid, base_id)
            return _WitnessCondition(inner, lid, witness)

        threading.Lock = make_lock  # type: ignore[assignment]
        threading.RLock = make_rlock  # type: ignore[assignment]
        threading.Condition = make_condition  # type: ignore[assignment]
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        threading.Lock = _REAL_LOCK  # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
        threading.Condition = _REAL_CONDITION  # type: ignore[assignment]


class _WitnessLock:
    """Lock/RLock wrapper feeding the witness.  Undeclared attributes
    delegate to the real primitive (Condition's ``_is_owned`` /
    ``_release_save`` probes keep working on RLocks)."""

    def __init__(self, inner, lock_id: str, witness: LockOrderWitness):
        self._inner = inner
        self.lock_id = lock_id
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._witness.on_acquired(
                self.lock_id, time.perf_counter() - t0
            )
        return ok

    def release(self) -> None:
        self._inner.release()
        self._witness.on_released(self.lock_id)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _WitnessCondition:
    """Condition wrapper: acquisition records like a lock; ``wait``
    additionally records held-lock blocking and keeps the held stack
    honest across the release-wait-reacquire cycle."""

    def __init__(self, inner, lock_id: str, witness: LockOrderWitness):
        self._inner = inner
        self.lock_id = lock_id
        self._witness = witness

    # -- lock surface ---------------------------------------------------------

    def acquire(self, *a, **kw):
        t0 = time.perf_counter()
        ok = self._inner.acquire(*a, **kw)
        if ok:
            self._witness.on_acquired(
                self.lock_id, time.perf_counter() - t0
            )
        return ok

    def release(self) -> None:
        self._inner.release()
        self._witness.on_released(self.lock_id)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- cv surface -----------------------------------------------------------

    def wait(self, timeout: Optional[float] = None):
        self._witness.on_cv_wait(self.lock_id)
        # the inner wait releases the REAL lock; mirror that on the
        # witnessed stack so reacquisition doesn't double-push
        self._witness.on_released(self.lock_id)
        try:
            return self._inner.wait(timeout)
        finally:
            self._witness.on_acquired(self.lock_id, 0.0)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._witness.on_cv_wait(self.lock_id)
        self._witness.on_released(self.lock_id)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._witness.on_acquired(self.lock_id, 0.0)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __getattr__(self, name):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# module-level convenience (chaos_smoke / soak / app endpoint)
# ---------------------------------------------------------------------------

DEFAULT_WITNESS: Optional[LockOrderWitness] = None
_STATIC_EDGES: Optional[Dict] = None


def install_witness(
    paths: Optional[List[str]] = None, blocking_ms: float = 50.0
) -> LockOrderWitness:
    """Build the id map from the real tree and install a process-wide
    witness.  Idempotent; returns the active witness."""
    global DEFAULT_WITNESS, _STATIC_EDGES
    if DEFAULT_WITNESS is not None:
        return DEFAULT_WITNESS
    id_map, aliases, edges = build_lock_id_map(paths)
    _STATIC_EDGES = edges
    DEFAULT_WITNESS = LockOrderWitness(
        id_map, aliases, blocking_ms=blocking_ms
    ).install()
    return DEFAULT_WITNESS


def witness_snapshot() -> Optional[Dict[str, Any]]:
    """The active witness's dump, cross-checked against the static graph
    (None when no witness is installed)."""
    if DEFAULT_WITNESS is None:
        return None
    return DEFAULT_WITNESS.snapshot(static_edges=_STATIC_EDGES)


def maybe_install_from_env() -> Optional[LockOrderWitness]:
    """``DOCQA_RACE_WITNESS=1`` installs the witness at service boot —
    the soak harness then reads ``GET /api/witness``."""
    if os.environ.get("DOCQA_RACE_WITNESS", "") in ("1", "true", "yes"):
        return install_witness()
    return None
