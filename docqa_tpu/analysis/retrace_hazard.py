"""retrace-hazard: compile-cache discipline for jit construction and
static arguments.

A serving path owes every jit root a WARM, REUSED compilation cache
(docs/PERF.md: one trace+compile costs seconds on a real chip; a retrace
inside a request is a latency cliff the admission deadline then reads as
an outage).  The compile audit (``analysis/compile_audit.py``) proves the
steady state retrace-free; this rule catches the construction patterns
that defeat the cache before they ship:

1. **jit inside a loop** — ``jax.jit(f)`` / ``pjit(f)`` constructed in a
   ``for``/``while`` body builds a fresh wrapper (and an empty cache)
   every iteration.  Hoist the construction; only the *call* belongs in
   the loop.
2. **construct-and-invoke** — ``jax.jit(f)(x)`` in one expression: the
   wrapper (and its cache) dies with the expression, so every execution
   of that line retraces.  Cache the wrapper (module global, ``self``
   attribute, or the ``_fns`` dict idiom every engine here uses).
   AOT chains (``jax.jit(f).lower(...)``) are exempt — lowering once is
   the sanctioned audit/ahead-of-time pattern.
3. **unhashable static argument** — a call site passing a list/dict/set
   literal in a position the wrapper marks static
   (``static_argnums``/``static_argnames``): jit hashes static values,
   so this raises at runtime on the first call.
4. **per-value retrace on a static argument** — a static position fed by
   ``len(...)`` or an enclosing loop variable retraces once per distinct
   value (the cache keys on the VALUE of a static, not its shape).

Wrapper bindings are tracked through the module: decorated defs
(``@jax.jit`` / ``@partial(jax.jit, static_argnums=...)``), assignments
(``fn = jax.jit(f, static_argnames=("k",))``, including ``self._fn =``),
and the calls checked are the same-module call sites of those names —
the no-guess contract of the chassis (an import-crossing call is checked
in the defining module when it, too, is in scope).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
    dotted_name,
)
# Construction rules cover the CACHED wrappers only: ``shard_map`` builds
# a plain traceable callable with no compile cache of its own, and the
# canonical idiom applies it immediately inside an enclosing jit (the
# construction re-runs per TRACE, not per call) — flagging it would mark
# every sharded kernel in the tree.
_CACHED_WRAPPERS = frozenset({"jit", "pjit"})


def _jit_call(module, node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` / ``pjit(...)`` Call node, or None.  Unwraps
    ``functools.partial(jax.jit, ...)`` the way jit-purity does."""
    if not isinstance(node, ast.Call):
        return None
    name = call_name(node)
    tail = module.resolve_alias(name).rsplit(".", 1)[-1] if name else ""
    if tail in _CACHED_WRAPPERS:
        return node
    if tail == "partial" and node.args:
        inner = node.args[0]
        if isinstance(inner, (ast.Name, ast.Attribute)):
            inner_tail = module.resolve_alias(
                dotted_name(inner)
            ).rsplit(".", 1)[-1]
            if inner_tail in _CACHED_WRAPPERS:
                return node
    return None


def _static_spec(module, jit_node: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static positional indices, static argnames) declared on a jit
    call/decorator; unresolvable (computed) specs return empty sets."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in jit_node.keywords:
        if kw.arg == "static_argnums":
            for elt in _literal_elts(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    nums.add(elt.value)
        elif kw.arg == "static_argnames":
            for elt in _literal_elts(kw.value):
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, str
                ):
                    names.add(elt.value)
    return nums, names


def _literal_elts(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return list(node.elts)
    return [node]


class RetraceHazardChecker:
    rule = "retrace-hazard"

    def check(self, package: Package) -> List[Finding]:
        out: List[Finding] = []
        for module in package.modules:
            self._check_module(module, out)
        return out

    # -- per-module ----------------------------------------------------------

    def _check_module(self, module, out: List[Finding]) -> None:
        # name -> (static nums incl. any self offset, static names)
        bindings: Dict[str, Tuple[Set[int], Set[str]]] = {}

        # decorated defs: @jax.jit / @partial(jax.jit, static_argnums=...)
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                jc = _jit_call(module, dec) if isinstance(
                    dec, ast.Call
                ) else None
                if jc is None and isinstance(dec, (ast.Name, ast.Attribute)):
                    tail = module.resolve_alias(
                        dotted_name(dec)
                    ).rsplit(".", 1)[-1]
                    if tail in _CACHED_WRAPPERS:
                        bindings[node.name] = (set(), set())
                        continue
                if jc is not None:
                    bindings[node.name] = _static_spec(module, jc)

        # assignments: fn = jax.jit(f, ...), self._fn = jax.jit(...)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            jc = _jit_call(module, node.value)
            if jc is None:
                continue
            spec = _static_spec(module, jc)
            for target in node.targets:
                name = dotted_name(target)
                if name:
                    bindings[name.rsplit(".", 1)[-1]] = spec
                    bindings[name] = spec

        self._construction_hazards(module, out)
        if any(spec[0] or spec[1] for spec in bindings.values()):
            self._static_hazards(module, bindings, out)

    def _construction_hazards(self, module, out: List[Finding]) -> None:
        """Rules 1-2: loop construction and construct-and-invoke."""

        # annotate loop membership + enclosing function with one walk
        def walk(node, in_loop: bool, qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                child_loop = in_loop
                child_qual = qual
                if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                    child_loop = True
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    child_qual = (
                        f"{qual}.{child.name}" if qual != "<module>"
                        else child.name
                    )
                    child_loop = False  # a def resets loop context
                if isinstance(child, ast.Call):
                    jc = _jit_call(module, child)
                    if jc is child and child_loop:
                        out.append(
                            Finding(
                                self.rule, module.relpath, child.lineno,
                                qual,
                                "jax.jit constructed inside a loop — a "
                                "fresh wrapper discards the compile "
                                "cache every iteration; hoist the "
                                "construction out of the loop",
                            )
                        )
                    # construct-and-invoke: func of THIS call is a jit call
                    if isinstance(child.func, ast.Call) and _jit_call(
                        module, child.func
                    ):
                        out.append(
                            Finding(
                                self.rule, module.relpath, child.lineno,
                                qual,
                                "jit-wrapped function constructed and "
                                "invoked in one expression — the compiled "
                                "program cannot be reused across calls; "
                                "cache the wrapper and call that",
                            )
                        )
                walk(child, child_loop, child_qual)

        walk(module.tree, False, "<module>")

    def _static_hazards(
        self, module, bindings: Dict[str, Tuple[Set[int], Set[str]]],
        out: List[Finding],
    ) -> None:
        """Rules 3-4 at same-module call sites of known jit bindings."""

        def visit(node, loop_vars: Set[str], qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                child_vars = loop_vars
                child_qual = qual
                if isinstance(child, (ast.For, ast.AsyncFor)):
                    child_vars = loop_vars | {
                        n.id
                        for n in ast.walk(child.target)
                        if isinstance(n, ast.Name)
                    }
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    child_qual = (
                        f"{qual}.{child.name}" if qual != "<module>"
                        else child.name
                    )
                    child_vars = set()
                if isinstance(child, ast.Call):
                    name = call_name(child)
                    spec = bindings.get(name) or bindings.get(
                        name.rsplit(".", 1)[-1] if name else ""
                    )
                    if spec and (spec[0] or spec[1]):
                        self._check_call(
                            module, child, spec, child_vars, child_qual, out
                        )
                visit(child, child_vars, child_qual)

        visit(module.tree, set(), "<module>")

    def _check_call(
        self, module, node: ast.Call, spec, loop_vars: Set[str],
        qual: str, out: List[Finding],
    ) -> None:
        nums, names = spec
        static_args: List[Tuple[str, ast.AST]] = []
        for i, arg in enumerate(node.args):
            if i in nums:
                static_args.append((f"position {i}", arg))
        for kw in node.keywords:
            if kw.arg in names:
                static_args.append((f"'{kw.arg}'", kw.value))
        for where, arg in static_args:
            if isinstance(arg, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                ast.DictComp, ast.SetComp)):
                out.append(
                    Finding(
                        self.rule, module.relpath, arg.lineno, qual,
                        f"unhashable literal in static argument {where} — "
                        "jit hashes static values; pass a tuple or mark "
                        "the argument non-static",
                    )
                )
                continue
            varying = None
            if isinstance(arg, ast.Call) and call_name(arg) == "len":
                varying = "len(...)"
            elif isinstance(arg, ast.Name) and arg.id in loop_vars:
                varying = f"loop variable '{arg.id}'"
            if varying:
                out.append(
                    Finding(
                        self.rule, module.relpath, arg.lineno, qual,
                        f"static argument {where} takes {varying} — the "
                        "cache keys on each distinct static VALUE, so "
                        "this retraces per call; bucket the value or "
                        "make it a traced argument",
                    )
                )
