"""wire-safety: unserializable values must not reach the wire.

``json.dumps`` fails loud on locks and Trace objects but SILENTLY
miscarries the subtle cases: a JAX device array blocks the event loop
on implicit device-to-host transfer before TypeError-ing, a numpy
scalar serializes fine on one numpy version and raises on another, and
``float("nan")`` produces ``NaN`` — a token that is NOT JSON and that
strict parsers (and the perf-gate's ``json.load``) reject.  This rule
flows coarse type facts to the three serialization boundaries —
``json_response(...)``, ``publish(queue, body)`` / ``_publish``, and
``_journal_write(queue, record)`` — and flags:

* device arrays (any value produced by a ``jax.*`` / ``jnp.*`` call),
* numpy scalars and arrays (``np.mean`` et al., ``np.array``/``zeros``),
* locks and other ``threading`` primitives,
* ``Trace`` / ``Span`` objects (``obs.new_trace(...)`` and friends),
* non-finite floats (``float("nan"/"inf")``, ``math.inf``/``math.nan``).

A payload is sanctioned when it is wrapped in ``to_wire(...)`` at the
call site, or when the called function's own body routes through
``to_wire`` (the ``service/app.py`` ``json_response`` wrapper) —
coercion at the boundary is the fix this rule exists to enforce, so it
must recognize the fix.  Facts are per-function and deliberately
shallow: a value this rule cannot type is silently trusted; every
finding names a concrete producer expression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from docqa_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    Package,
    call_name,
)

_NUMPY_SCALAR_FNS = frozenset(
    {
        "mean", "sum", "min", "max", "median", "percentile", "quantile",
        "std", "var", "dot", "prod", "float32", "float64", "int32",
        "int64",
    }
)
_NUMPY_ARRAY_FNS = frozenset(
    {"array", "zeros", "ones", "asarray", "arange", "concatenate",
     "stack", "full", "empty"}
)
_LOCK_FNS = frozenset(
    {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
     "Event", "Barrier"}
)
_TRACE_FNS = frozenset({"new_trace", "Trace", "Span", "new_span"})
_CLEAN_WRAPPERS = frozenset(
    {"float", "int", "str", "bool", "list", "dict", "item", "tolist",
     "to_wire", "len", "round", "sorted", "repr"}
)


def _call_kind(node: ast.Call, fn: FunctionInfo) -> Optional[str]:
    """Coarse type of a call's result, or None when untyped."""
    dotted = call_name(node)
    if not dotted:
        return None
    head = dotted.split(".", 1)[0]
    tail = dotted.rsplit(".", 1)[-1]
    origin = fn.module.resolve_alias(dotted)
    origin_head = origin.split(".", 1)[0]
    if origin_head == "jax" or origin.startswith("jax."):
        return "device array"
    if head in ("jnp", "jax") or ".numpy." in origin:
        return "device array"
    if origin_head == "numpy" or head in ("np", "numpy"):
        if tail in _NUMPY_SCALAR_FNS:
            return "numpy scalar"
        if tail in _NUMPY_ARRAY_FNS:
            return "numpy array"
        return None
    if tail in _LOCK_FNS and (
        head in ("threading", "asyncio") or head == tail
    ):
        return "lock"
    if tail in _TRACE_FNS:
        return "trace/span object"
    if tail == "float" and node.args:
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(
            arg.value, str
        ) and arg.value.lstrip("+-").lower() in ("inf", "infinity", "nan"):
            return "non-finite float"
    return None


def _const_kind(node: ast.AST) -> Optional[str]:
    """math.inf / math.nan attribute reads."""
    if isinstance(node, ast.Attribute) and node.attr in ("inf", "nan"):
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("math", "np",
                                                      "numpy"):
            return "non-finite float"
    return None


def _gather_facts(fn: FunctionInfo) -> Dict[str, str]:
    facts: Dict[str, str] = {}
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        kind: Optional[str] = None
        if isinstance(node.value, ast.Call):
            kind = _call_kind(node.value, fn)
        else:
            kind = _const_kind(node.value)
        if kind is not None:
            facts[tgt.id] = kind
        else:
            facts.pop(tgt.id, None)  # reassigned to something untyped
    return facts


def _wraps_to_wire(fn: FunctionInfo) -> bool:
    return any(
        isinstance(n, ast.Call)
        and call_name(n).rsplit(".", 1)[-1] == "to_wire"
        for n in ast.walk(fn.node)
    )


class WireSafetyChecker:
    rule = "wire-safety"

    def check(self, package: Package) -> List[Finding]:
        # bare names of functions whose body coerces via to_wire —
        # calling THEM is a sanctioned boundary.
        sanctioned = {
            fn.name for fn in package.functions if _wraps_to_wire(fn)
        }
        out: List[Finding] = []
        for fn in package.functions:
            facts = _gather_facts(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                tail = call_name(node).rsplit(".", 1)[-1]
                payload: Optional[ast.AST] = None
                boundary = ""
                if tail == "json_response" and node.args:
                    payload = node.args[0]
                    boundary = "json_response"
                elif tail in ("publish", "_publish") and len(
                    node.args
                ) >= 2:
                    payload = node.args[1]
                    boundary = "broker publish"
                elif tail == "_journal_write" and len(node.args) >= 2:
                    payload = node.args[1]
                    boundary = "journal write"
                if payload is None:
                    continue
                if tail != "json_response" and tail in sanctioned:
                    continue
                if (
                    tail == "json_response"
                    and call_name(node) == "json_response"
                    and "json_response" in sanctioned
                    and fn.name != "json_response"
                ):
                    # the local to_wire-coercing wrapper
                    continue
                self._check_expr(
                    fn, facts, payload, boundary, node.lineno, out
                )
        return out

    def _check_expr(
        self,
        fn: FunctionInfo,
        facts: Dict[str, str],
        expr: ast.AST,
        boundary: str,
        lineno: int,
        out: List[Finding],
    ) -> None:
        kind: Optional[str] = None
        if isinstance(expr, ast.Name):
            kind = facts.get(expr.id)
        elif isinstance(expr, ast.Call):
            tail = call_name(expr).rsplit(".", 1)[-1]
            if tail in _CLEAN_WRAPPERS:
                return  # float(x), x.item(), to_wire(x), ... are safe
            kind = _call_kind(expr, fn)
        elif isinstance(expr, ast.Dict):
            for v in expr.values:
                if v is not None:
                    self._check_expr(fn, facts, v, boundary, lineno, out)
            return
        elif isinstance(expr, (ast.List, ast.Tuple, ast.Set)):
            for v in expr.elts:
                self._check_expr(fn, facts, v, boundary, lineno, out)
            return
        else:
            kind = _const_kind(expr)
        if kind is None:
            return
        if fn.module.is_suppressed(self.rule, lineno):
            return
        out.append(
            Finding(
                self.rule,
                fn.module.relpath,
                lineno,
                fn.qualname,
                f"{kind} crosses the wire at a {boundary} boundary — "
                "coerce with to_wire() before serializing",
            )
        )
