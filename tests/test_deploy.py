"""Deployment artifacts (VERDICT round-3 item 7).

Docker itself is not available in this image, so these validate the
artifacts' CONTRACTS: the compose file parses, its env vars name real
config fields (a typo'd ``DOCQA_...`` overlay would be silently ignored at
boot), the Dockerfile copies everything the entrypoint imports, and the
entrypoint/healthcheck reference real files and routes.

Reference parity surface: ``docker-compose.yml:5-51`` +
``synthese-comparative/Dockerfile``.
"""

import os
import re

import pytest

yaml = pytest.importorskip("yaml")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPOSE = os.path.join(REPO, "deploy", "docker-compose.yml")
DOCKERFILE = os.path.join(REPO, "deploy", "Dockerfile")


def _compose():
    with open(COMPOSE) as f:
        return yaml.safe_load(f)


def _env_overlay_resolves(name: str) -> bool:
    """True iff ``DOCQA_SECTION__FIELD`` names a real config field."""
    from docqa_tpu.config import Config

    m = re.fullmatch(r"DOCQA_([A-Z_]+?)__([A-Z_]+)", name)
    if not m:
        return False
    section, field_name = m.group(1).lower(), m.group(2).lower()
    cfg = Config()
    if not hasattr(cfg, section):
        return False
    return hasattr(getattr(cfg, section), field_name)


class TestCompose:
    def test_parses_and_has_expected_services(self):
        blob = _compose()
        services = blob["services"]
        assert {"docqa", "docqa-multihost", "postgres", "rabbitmq"} <= set(
            services
        )
        # single-host service carries no multihost profile (up by default)
        assert "profiles" not in services["docqa"]
        for svc in ("docqa-multihost", "postgres", "rabbitmq"):
            assert services[svc].get("profiles") == ["multihost"]

    def test_env_overlays_name_real_config_fields(self):
        services = _compose()["services"]
        checked = 0
        for svc in services.values():
            for key in svc.get("environment", {}):
                if key.startswith("DOCQA_"):
                    assert _env_overlay_resolves(key), key
                    checked += 1
        assert checked >= 5  # work_dir x2, registry url, broker x3

    def test_multihost_wires_postgres_and_amqp(self):
        env = _compose()["services"]["docqa-multihost"]["environment"]
        assert env["DOCQA_REGISTRY__URL"].startswith("postgresql://")
        assert env["DOCQA_BROKER__BACKEND"] == "amqp"
        assert env["DOCQA_BROKER__AMQP_HOST"] == "rabbitmq"
        deps = _compose()["services"]["docqa-multihost"]["depends_on"]
        assert deps["postgres"]["condition"] == "service_healthy"
        assert deps["rabbitmq"]["condition"] == "service_healthy"

    def test_external_services_have_healthchecks(self):
        services = _compose()["services"]
        for svc in ("postgres", "rabbitmq"):
            assert "healthcheck" in services[svc]


class TestDockerfile:
    def test_copies_cover_the_entrypoint_imports(self):
        src = open(DOCKERFILE).read()
        copied = set(re.findall(r"^COPY\s+(\S+)\s", src, re.M))
        assert {"docqa_tpu", "scripts", "native"} <= copied
        # entrypoint script exists in the repo at the copied path
        cmd = re.search(r'CMD \["python", "([^"]+)"', src)
        assert cmd and os.path.exists(os.path.join(REPO, cmd.group(1)))

    def test_healthcheck_hits_a_real_route(self):
        src = open(DOCKERFILE).read()
        assert "/health" in src  # app.py exposes GET /health
        from docqa_tpu.service import app as app_mod

        assert "/health" in open(app_mod.__file__).read()

    def test_default_env_overlays_resolve(self):
        src = open(DOCKERFILE).read()
        for name in re.findall(r"(DOCQA_[A-Z_]+?__[A-Z_]+)=", src):
            assert _env_overlay_resolves(name), name
