"""Paged KV cache (engines/paged.py + the batcher integration).

The contracts that matter:

* allocator accounting is exact — all-or-nothing allocation, LIFO reuse
  after mixed retirement order (fragmentation), idempotent release, and
  a double free RAISES instead of silently inflating the pool;
* a lane GROWS past its initial allocation mid-decode and still matches
  the solo engine token for token;
* pool exhaustion is typed and deadline-aware — an oversized pool wait
  sheds on the deadline, an overcommitted pool sheds
  :class:`BlockPoolExhausted` on the handle, and a submit into a dry
  pool+full queue gets the typed 503;
* drain / steal / stop / kill / worker-death free every block exactly
  once (zero leaked blocks — the accounting IS the leak detector).
"""

import time

import pytest

from docqa_tpu.config import DecoderConfig, GenerateConfig
from docqa_tpu.engines.generate import GenerateEngine
from docqa_tpu.engines.paged import BlockAllocator, OutOfBlocks
from docqa_tpu.engines.serve import BlockPoolExhausted, ContinuousBatcher
from docqa_tpu.resilience.deadline import Deadline, DeadlineExceeded

CFG = DecoderConfig(
    vocab_size=128, hidden_dim=64, num_layers=2, num_heads=4,
    num_kv_heads=2, head_dim=16, mlp_dim=128, max_seq_len=256,
    dtype="float32",
)
GEN = GenerateConfig(temperature=0.0, eos_id=2)


@pytest.fixture(scope="module")
def engine():
    return GenerateEngine(CFG, GEN, seed=7)


class TestBlockAllocator:
    def test_all_or_nothing_and_stats(self):
        a = BlockAllocator(n_blocks=8, block_size=4)
        t = a.new_table()
        t.ensure(9)  # 3 blocks
        assert len(t.blocks) == 3 and t.capacity == 12
        assert a.blocks_in_use == 3 and a.n_free == 5
        t.ensure(10)  # already covered: no growth
        assert len(t.blocks) == 3
        with pytest.raises(OutOfBlocks):
            t.ensure(8 * 4 + 1)  # past the whole pool
        # the failed grow took nothing (all-or-nothing)
        assert a.blocks_in_use == 3 and a.n_free == 5

    def test_fragmentation_reuse_after_mixed_retirement(self):
        """Free in an order different from allocation; the pool must
        hand every block back out (no fragmentation loss — block ids
        are interchangeable, which is the whole point of paging)."""
        a = BlockAllocator(n_blocks=6, block_size=2)
        t1, t2, t3 = a.new_table(), a.new_table(), a.new_table()
        t1.ensure(4)
        t2.ensure(4)
        t3.ensure(4)
        assert a.n_free == 0
        # retire the MIDDLE one first, then the first
        t2.release()
        t1.release()
        big = a.new_table()
        big.ensure(8)  # 4 blocks, spanning both freed tables' blocks
        assert a.blocks_in_use == 6
        big.release()
        t3.release()
        assert a.blocks_in_use == 0 and a.n_free == 6

    def test_release_idempotent_double_free_raises(self):
        a = BlockAllocator(n_blocks=4, block_size=2)
        t = a.new_table()
        t.ensure(6)
        t.release()
        t.release()  # idempotent: second release is a no-op
        assert a.blocks_in_use == 0
        # a forged second free of the same block ids must RAISE
        t2 = a.new_table()
        t2.ensure(2)
        stolen = list(t2.blocks)
        t2.release()
        forged = a.new_table()
        forged.blocks = stolen
        with pytest.raises(RuntimeError, match="double free"):
            forged.release()

    def test_grow_after_release_refused(self):
        a = BlockAllocator(n_blocks=4, block_size=2)
        t = a.new_table()
        t.ensure(2)
        t.release()
        with pytest.raises(OutOfBlocks):
            t.ensure(4)


class TestPagedBatcher:
    def test_grow_past_initial_allocation_matches_solo(self, engine):
        """Tiny blocks + a long generation: the lane's table must grow
        several times mid-decode and output stays exactly solo-greedy."""
        b = ContinuousBatcher(
            engine, n_slots=2, chunk=4, cache_len=256, kv_block_size=8
        )
        try:
            prompt = [3, 5, 9, 4]
            solo = engine.generate_ids([prompt], max_new_tokens=96)[0]
            got = b.submit_ids(prompt, max_new_tokens=96).result(timeout=300)
            assert got == solo
            # the lane needed (4 + 96) tokens = 13 blocks of 8 — far past
            # the initial prompt-plus-margin allocation
            assert b._alloc.blocks_in_use == 0  # retired: all freed
        finally:
            b.stop()

    @pytest.mark.slow  # 8-request mixed-length burst (~17 s on this
    # 1-core host); grow-past-initial / replica-kill / QoS-preemption
    # tests keep the overcommit path in the tier-1 budget.
    def test_overcommitted_pool_mixed_lengths(self, engine):
        """A pool well under worst case still serves a burst of mixed
        lengths — blocks freed by short requests feed long ones (the
        HBM-overcommit economics ROADMAP item 1 claims)."""
        b = ContinuousBatcher(
            engine, n_slots=4, chunk=4, cache_len=256, kv_block_size=16,
            kv_pool_tokens=2 * 256,  # half of worst case (4 x 256)
        )
        try:
            prompts = [[3 + i, 5 + i % 7, 9] for i in range(8)]
            budgets = [4, 30, 8, 2, 22, 6, 40, 12]
            solo = [
                engine.generate_ids([p], max_new_tokens=m)[0]
                for p, m in zip(prompts, budgets)
            ]
            handles = [
                b.submit_ids(p, max_new_tokens=m)
                for p, m in zip(prompts, budgets)
            ]
            got = [h.result(timeout=300) for h in handles]
            assert got == solo
            assert b._alloc.blocks_in_use == 0
        finally:
            b.stop()

    def test_pool_wait_sheds_on_deadline(self, engine):
        """A request waiting for blocks keeps its deadline semantics:
        when the budget lapses while the pool is held by a long
        decode, it sheds DeadlineExceeded — typed, deadline-aware, and
        the batcher keeps serving."""
        b = ContinuousBatcher(
            engine, n_slots=2, chunk=4, cache_len=256, kv_block_size=16,
            kv_pool_tokens=256,  # exactly one maximal lane
        )
        try:
            hog = b.submit_ids([3, 5, 9], max_new_tokens=120)
            waiter = b.submit_ids(
                [4, 6], max_new_tokens=4, deadline=Deadline.after(0.4)
            )
            with pytest.raises(DeadlineExceeded):
                waiter.result(timeout=60)
            assert len(hog.result(timeout=300)) > 0  # hog unaffected
            assert b._alloc.blocks_in_use == 0
        finally:
            b.stop()

    def test_submit_exhausted_pool_full_queue_typed(self, engine):
        """Queue full AND pool dry: the 503 is the TYPED pool-exhaustion
        subclass, so operators see the real bottleneck — and a
        block-starved queued request admits as soon as blocks free."""
        b = ContinuousBatcher(
            engine, n_slots=1, chunk=4, cache_len=256, kv_block_size=16,
            kv_pool_tokens=256, max_queue=1,
        )
        try:
            # hold the whole pool from outside the slot set — the
            # deterministic stand-in for lanes having grown over it
            hold = b._alloc.new_table()
            hold.ensure(256)
            assert b._alloc.n_free == 0
            queued = b.submit_ids([4, 6], max_new_tokens=4)  # fills queue
            with pytest.raises(BlockPoolExhausted):
                b.submit_ids([5], max_new_tokens=2)
            # starved, not lost: the queued request stays pending...
            time.sleep(0.3)
            assert not queued._req.done.is_set()
            # ...and admits the moment the pool refills
            hold.release()
            assert len(queued.result(timeout=120)) > 0
        finally:
            b.stop()

    def test_zero_leak_after_drain(self, engine):
        b = ContinuousBatcher(engine, n_slots=2, chunk=4, cache_len=128)
        try:
            handles = [
                b.submit_ids([3 + i, 5], max_new_tokens=12) for i in range(5)
            ]
            assert b.drain(timeout=120)
            for h in handles:
                assert len(h.result(timeout=5)) > 0
            assert b._alloc.blocks_in_use == 0
            b.resume()
            # still serves after the drain cycle
            assert len(
                b.submit_ids([3, 5], max_new_tokens=4).result(timeout=120)
            ) > 0
        finally:
            b.stop()

    def test_zero_leak_after_steal_and_stop(self, engine):
        b = ContinuousBatcher(
            engine, n_slots=2, chunk=4, cache_len=128, max_queue=16
        )
        b.drain(timeout=60)  # quiesce so queued work stays queued
        b.resume()
        b2 = ContinuousBatcher(engine, n_slots=2, chunk=4, cache_len=128)
        try:
            b.drain(timeout=60)
        finally:
            pass
        # queued-but-unadmitted requests steal cleanly (they own no
        # blocks) and re-admit elsewhere; stop() closes the accounting
        try:
            with b._cv:
                pass
            stolen = b.steal_queued()
            assert stolen == []  # drained: nothing queued
            b.stop()
            assert b._alloc.blocks_in_use == 0
            out = b2.submit_ids([3, 5], max_new_tokens=4).result(timeout=120)
            assert len(out) > 0
        finally:
            b2.stop()
            assert b2._alloc.blocks_in_use == 0

    def test_zero_leak_after_kill_with_live_requests(self, engine):
        """kill() (the pool's wedged-replica fail-fast) fails everything
        typed AND closes the block accounting exactly once — the pool
        rescue that follows builds a fresh batcher+pool, so the old
        allocator must balance on its own."""
        b = ContinuousBatcher(
            engine, n_slots=2, chunk=4, cache_len=128, max_queue=16
        )
        handles = [
            b.submit_ids([3 + i, 5], max_new_tokens=60) for i in range(6)
        ]
        # let at least one admission happen
        deadline = time.monotonic() + 30
        while not b._alloc.blocks_in_use and time.monotonic() < deadline:
            time.sleep(0.002)
        assert b._alloc.blocks_in_use > 0
        b.kill(RuntimeError("wedged"))
        for h in handles:
            with pytest.raises(Exception):
                h.result(timeout=10)
        # the (possibly mid-iteration) worker exits at its next wakeup;
        # accounting is already closed and stays closed
        assert b._alloc.blocks_in_use == 0

    def test_worker_death_frees_blocks_and_rescues_queue(self, engine):
        """A crashed worker's death handler frees slot blocks exactly
        once and offers queued requests to the rescue hook — the pool
        failover path — with no block attached to them."""
        b = ContinuousBatcher(
            engine, n_slots=2, chunk=4, cache_len=128, max_queue=16
        )
        rescued = []
        b.on_worker_death = lambda _b, queued: rescued.extend(queued) or []
        handles = [
            b.submit_ids([3 + i, 5], max_new_tokens=60) for i in range(6)
        ]
        deadline = time.monotonic() + 30
        while not b._alloc.blocks_in_use and time.monotonic() < deadline:
            time.sleep(0.002)
        # crash the worker loop from outside (same observable effect as
        # an internal fault: _run's catch-all routes to _worker_died)
        import threading

        t = threading.Thread(
            target=b._worker_died, args=(RuntimeError("crash"),)
        )
        t.start()
        t.join(timeout=30)
        b._stopped = True
        assert b._alloc.blocks_in_use == 0
        # admitted requests failed typed; queued ones went to the hook.
        # Rescued requests' handles never resolve HERE by design (the
        # hook took ownership — in the pool path they re-submit on
        # another replica), so waiting on them only burns the timeout:
        # count them via the hook's list instead.
        rescued_ids = {id(r) for r in rescued}
        n_failed = 0
        for h in handles:
            if id(h._req) in rescued_ids:
                continue
            try:
                h.result(timeout=10)
            except Exception:
                n_failed += 1
        assert n_failed + len(rescued) >= 4

    def test_pool_replica_kill_rebuild_no_leak(self, engine):
        """End to end through EnginePool: kill a replica mid-traffic,
        let the pool rebuild it, and assert zero lost requests AND zero
        leaked blocks on every batcher generation."""
        from docqa_tpu.engines.pool import EnginePool

        pool = EnginePool(
            engine, replicas=2, n_slots=2, chunk=4, cache_len=128,
            canary_interval_s=600.0, health_interval_s=0.05,
        )
        batchers = [r.batcher for r in pool._replicas]
        try:
            handles = [
                pool.submit_ids([3 + i, 5], max_new_tokens=8)
                for i in range(6)
            ]
            outcomes = 0
            for h in handles:
                try:
                    h.result(timeout=120)
                    outcomes += 1
                except Exception:
                    outcomes += 1  # typed failure is an outcome too
            assert outcomes == 6  # zero hung
        finally:
            pool.stop()
        for b in batchers:
            assert b._alloc.blocks_in_use == 0
