"""Worker process for tests/test_multihost.py — NOT a test module.

Each of the two OS processes runs this script: force the CPU backend
(defeating the environment's accelerator hook), join the distributed
runtime through the framework's own ``multihost_init``, build the global
mesh, and run a cross-process reduction whose result proves bytes moved
between the processes.
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # before any backend init

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main() -> None:
    port, process_id = sys.argv[1], int(sys.argv[2])
    sys.path.insert(0, sys.argv[3])  # repo root

    from docqa_tpu.runtime.mesh import make_mesh, multihost_init

    assert multihost_init(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=process_id,
    )
    assert jax.process_count() == 2, jax.process_count()
    ld = jax.local_device_count()
    assert jax.device_count() == 2 * ld

    ctx = make_mesh()  # over ALL global devices — the cross-process mesh
    assert ctx.n_devices == jax.device_count()

    # each process contributes (process_index + 1) per local device; the
    # global sum must therefore be ld*1 + ld*2 = 3*ld — a value no single
    # process could compute without the other's shard
    local = np.full((ld,), float(jax.process_index() + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        ctx.row_sharded, local, (jax.device_count(),)
    )
    try:
        total = jax.jit(
            jnp.sum, out_shardings=NamedSharding(ctx.mesh, P())
        )(arr)
    except Exception as e:  # noqa: BLE001 - classified below, re-raised else
        # Some jaxlib builds implement the distributed RUNTIME (init,
        # process discovery, global mesh — all asserted above) but not
        # multiprocess COLLECTIVES on the CPU backend.  That is an
        # environment limitation, not a framework regression: report it
        # distinctly (rc=3) so the test can skip instead of fail, without
        # masking real crashes (any other failure still exits nonzero).
        if "aren't implemented on the CPU backend" in str(e):
            print("MULTIHOST_UNSUPPORTED cpu-collectives", flush=True)
            sys.exit(3)
        raise
    print(f"MULTIHOST_OK {float(total)}", flush=True)


if __name__ == "__main__":
    main()
