"""Answer routing (docqa-lexroute, ``engines/router.py``).

The router's contract has four load-bearing edges:

* the text-stage ``decide()`` must hold the precision floor on the
  checked-in labeled mix (``data/routing_mix.jsonl`` — authored like the
  deid HELDOUT split, never tuned against), with generative cues taking
  precedence over digit runs ("why is patient 12345678 ..." is a
  generative question ABOUT an MRN);
* the evidence gate demotes — never fails — an extractive decision the
  retrieved context can't actually answer;
* ``extractive_answer`` is ONE implementation with two call sites: the
  PR 1 degraded-mode fallback (behavior pinned here byte-for-byte) and
  the routed fast path;
* the wire shape: ``route`` is an opt-in key on routed-extractive
  answers only — generative and degraded responses keep their exact
  pre-lexroute contracts.
"""

import json
import os

import numpy as np
import pytest

from docqa_tpu.engines.router import (
    ROUTE_EXTRACTIVE,
    ROUTE_GENERATIVE,
    AnswerRouter,
    RouteDecision,
    extractive_answer,
    extractive_confidence,
    fuse_scores,
)

MIX_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "data",
    "routing_mix.jsonl",
)


def _load_mix():
    with open(MIX_PATH, encoding="utf-8") as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# ---------------------------------------------------------------------------
# Text-stage decisions
# ---------------------------------------------------------------------------


class TestDecide:
    def test_mix_precision_floor(self):
        # the perf gate pins this as routing_precision_smoke; keep the
        # same floor here so a router edit fails fast in the unit suite
        router = AnswerRouter()
        tp = fp = fn = 0
        for ex in _load_mix():
            want = ex["label"] == "extractive"
            got = router.decide(ex["question"]).route == ROUTE_EXTRACTIVE
            tp += want and got
            fp += got and not want
            fn += want and not got
        assert tp / max(tp + fp, 1) >= 0.95, (tp, fp)
        assert tp / max(tp + fn, 1) >= 0.90, (tp, fn)

    def test_generative_cue_beats_digit_run(self):
        # precedence: an MRN inside a why-question stays generative
        d = AnswerRouter().decide("Why is patient 12345678 on dialysis?")
        assert d.route == ROUTE_GENERATIVE
        assert d.reason.startswith("generative_cue")

    def test_digit_run_routes_extractive(self):
        d = AnswerRouter().decide("Look up the record 77120034")
        assert d.route == ROUTE_EXTRACTIVE
        assert d.reason == "digit_run"
        # dotted phone groups count as one run
        d2 = AnswerRouter().decide("Whose chart lists 450.555.0142?")
        assert d2.route == ROUTE_EXTRACTIVE

    def test_quoted_exact_routes_extractive(self):
        d = AnswerRouter().decide('Find the note containing "chest pain"')
        assert d.route == ROUTE_EXTRACTIVE
        assert d.reason == "quoted_exact"

    def test_fr_lookup_cue_with_diacritics(self):
        d = AnswerRouter().decide(
            "Quel est le numéro de dossier du patient Tremblay ?"
        )
        assert d.route == ROUTE_EXTRACTIVE
        assert d.reason.startswith("lookup_cue")

    def test_empty_and_default_generative(self):
        r = AnswerRouter()
        assert r.decide("").route == ROUTE_GENERATIVE
        assert r.decide("").reason == "empty_question"
        # no cue at all: conservative default is the generative path
        d = r.decide("patient status overnight")
        assert d.route == ROUTE_GENERATIVE
        assert d.reason == "default_generative"

    def test_disabled_router_is_pre_lexroute_behavior(self):
        d = AnswerRouter(enabled=False).decide("What is the MRN of Okafor?")
        assert d.route == ROUTE_GENERATIVE
        assert d.reason == "router_disabled"


# ---------------------------------------------------------------------------
# Evidence gate (stage 2)
# ---------------------------------------------------------------------------

_EX = RouteDecision(ROUTE_EXTRACTIVE, 0.9, "digit_run")


class TestEvidenceGate:
    def test_no_chunks_demotes(self):
        d, ev = AnswerRouter().evidence_gate(_EX, "MRN 40081223?", [])
        assert d.route == ROUTE_GENERATIVE
        assert d.reason == "low_evidence"
        assert ev == 0.0

    def test_missing_identifier_demotes(self):
        # context covers the words but NOT the asked-for MRN: a lookup
        # the context can't answer must fall through to the decoder
        chunks = ["admission note for the patient, MRN redacted"]
        d, ev = AnswerRouter().evidence_gate(
            _EX, "What is MRN 40081223?", chunks
        )
        assert d.route == ROUTE_GENERATIVE
        assert ev <= 0.25

    def test_full_evidence_keeps_route(self):
        chunks = ["patient okafor mrn 40081223 admitted to ward b"]
        d, ev = AnswerRouter().evidence_gate(
            _EX, "What is the MRN of patient Okafor?", chunks
        )
        assert d.route == ROUTE_EXTRACTIVE
        assert ev >= 0.5

    def test_below_min_confidence_demotes(self):
        weak = RouteDecision(ROUTE_EXTRACTIVE, 0.6, "lookup_cue:dose of")
        d, _ = AnswerRouter(min_confidence=0.7).evidence_gate(
            weak, "dose of metformin?", ["metformin 850 mg dose"]
        )
        assert d.route == ROUTE_GENERATIVE
        assert d.reason == "below_min_confidence"

    def test_generative_decision_passes_through(self):
        gen = RouteDecision(ROUTE_GENERATIVE, 0.9, "generative_cue:why")
        d, _ = AnswerRouter().evidence_gate(gen, "why?", ["context"])
        assert d is gen


class TestExtractiveConfidence:
    def test_monotone_in_coverage(self):
        q = "metformin dose for patient silva"
        none = extractive_confidence(q, ["unrelated cardiology note"])
        part = extractive_confidence(q, ["metformin dose reviewed"])
        full = extractive_confidence(
            q, ["metformin 850 mg dose for patient silva"]
        )
        assert none < part < full == 1.0

    def test_empty_inputs(self):
        assert extractive_confidence("q", []) == 0.0
        # stopword-only question carries no checkable content
        assert extractive_confidence("what is the", ["anything"]) == 0.0

    def test_digit_term_gate_caps_confidence(self):
        # everything matches EXCEPT the identifier: capped hard
        q = "record 77120034 discharge summary"
        ev = extractive_confidence(q, ["record discharge summary"])
        assert ev <= 0.25


# ---------------------------------------------------------------------------
# Score fusion
# ---------------------------------------------------------------------------


class TestFuseScores:
    def test_union_minmax_and_tiebreak(self):
        dense = [(0.9, 1), (0.5, 2)]
        lexical = [(10.0, 2), (4.0, 3)]
        fused = fuse_scores(dense, lexical, alpha=0.5)
        # rows 1 and 2 both fuse to 0.5; deterministic tie-break on id
        assert [rid for _, rid in fused] == [1, 2, 3]
        assert fused[0][0] == pytest.approx(fused[1][0])

    def test_alpha_extremes(self):
        dense = [(0.9, 1), (0.5, 2)]
        lexical = [(10.0, 2), (4.0, 3)]
        # pure dense: dense order leads; absent row 3 ties at 0 with
        # row 2's normalized min — id tie-break keeps it deterministic
        assert [r for _, r in fuse_scores(dense, lexical, 1.0)] == [1, 2, 3]
        # pure lexical: row 2 leads; rows 1 and 3 tie at 0, id order
        assert [r for _, r in fuse_scores(dense, lexical, 0.0)] == [2, 1, 3]

    def test_k_truncation_and_empty_tiers(self):
        dense = [(0.9, 1), (0.5, 2)]
        assert len(fuse_scores(dense, [], 0.5, k=1)) == 1
        # one-sided fusion still ranks the populated tier
        assert [r for _, r in fuse_scores(dense, [], 0.5)] == [1, 2]
        assert fuse_scores([], [], 0.5) == []

    def test_degenerate_single_candidate(self):
        # min==max: normalization must not divide by zero
        fused = fuse_scores([(0.7, 5)], [(3.0, 5)], 0.6)
        assert fused == [(pytest.approx(1.0), 5)]


# ---------------------------------------------------------------------------
# Promoted extractive answerer (PR 1 degraded behavior pinned)
# ---------------------------------------------------------------------------


class TestPromotedAnswerer:
    def test_one_implementation_two_call_sites(self):
        # qa.py re-exports the SAME function object — not a copy that
        # could drift from the degraded-mode behavior the tests pin
        from docqa_tpu.service import qa as qa_mod

        assert qa_mod.extractive_answer is extractive_answer

    def test_degraded_behavior_pinned(self):
        # byte-for-byte the PR 1 fallback: join, truncate, FR empty-case
        assert extractive_answer(["a", "", "b"]) == "a\n\nb"
        assert extractive_answer(["x" * 1000], max_chars=600) == "x" * 600
        assert extractive_answer([]) == "Aucun contexte trouvé."
        # whitespace-only chunks strip to nothing -> same FR empty case
        assert extractive_answer(["", "  "]) == "Aucun contexte trouvé."


# ---------------------------------------------------------------------------
# QA-service wiring: route wire key, mode forwarding, degraded contract
# ---------------------------------------------------------------------------


class _Hit:
    def __init__(self, text, source):
        self.metadata = {"text_content": text, "source": source}


class _Enc:
    def encode_texts(self, texts):
        return np.zeros((len(texts), 4), np.float32)


class _Store:
    """Mode-aware fake store recording the forwarded retrieve kwargs."""

    count = 2
    supports_modes = True

    def __init__(self, chunks):
        self.chunks = chunks
        self.calls = []

    def search(self, emb, k=3, filters=None, mode=None, query_texts=None):
        self.calls.append({"mode": mode, "query_texts": query_texts})
        return [[_Hit(c, f"s{i}") for i, c in enumerate(self.chunks)]]


def _qa(store, router=AnswerRouter):
    from docqa_tpu.service.qa import QAService

    return QAService(
        _Enc(), store, None, None, use_fake_llm=True,
        router=router() if router else None,
    )


class TestRoutedWireShape:
    def test_routed_extractive_wire_shape(self):
        store = _Store(["patient okafor mrn 40081223 admitted ward b"])
        out = _qa(store).ask("What is the MRN of patient Okafor?")
        assert {"answer", "sources"} <= set(out)
        assert out["route"] == "extractive"
        assert "degraded" not in out
        # the answer IS the retrieved evidence (extractive_answer)
        assert "40081223" in out["answer"]
        # stage 1 picked the hybrid tier for the extractive candidate
        assert store.calls[0]["mode"] == "hybrid"
        assert store.calls[0]["query_texts"] == [
            "What is the MRN of patient Okafor?"
        ]

    def test_generative_keeps_reference_contract(self):
        store = _Store(["observation note for the overnight admission"])
        out = _qa(store).ask("Why was the patient admitted overnight?")
        assert {"answer", "sources"} <= set(out)
        assert "route" not in out  # opt-in key, extractive-routed only
        # generative questions retrieve on the serving default (dense)
        assert store.calls[0]["mode"] is None

    def test_evidence_demotion_serves_generative(self):
        # extractive text decision, but retrieval misses the identifier:
        # demote to the generative path — an answer, never an error
        store = _Store(["unrelated cardiology consult"])
        out = _qa(store).ask("What is the MRN of patient Okafor?")
        assert {"answer", "sources"} <= set(out)
        assert "route" not in out
        assert store.calls[0]["mode"] == "hybrid"  # stage 1 still tried

    def test_no_router_is_pre_lexroute_behavior(self):
        store = _Store(["patient okafor mrn 40081223"])
        out = _qa(store, router=None).ask("What is the MRN of Okafor?")
        assert "route" not in out
        assert store.calls[0]["mode"] is None

    def test_mode_not_forwarded_without_support(self):
        # a store that never declared supports_modes gets the exact
        # pre-lexroute call signature (no mode kwarg to choke on)
        class _Legacy:
            count = 1

            def __init__(self):
                self.kwargs = None

            def search(self, emb, k=3, filters=None):
                self.kwargs = {"k": k, "filters": filters}
                return [[_Hit("mrn 40081223 patient okafor chart", "s0")]]

        store = _Legacy()
        from docqa_tpu.service.qa import QAService

        qa = QAService(
            _Enc(), store, None, None, use_fake_llm=True,
            router=AnswerRouter(),
        )
        out = qa.ask("What is the MRN of patient Okafor?")
        assert out["route"] == "extractive"  # routing works on dense too
        assert store.kwargs == {"k": 3, "filters": None}

    def test_degraded_response_contract_unchanged(self):
        # generation fails AFTER retrieval: the degraded answer keeps the
        # PR 1 contract — degraded keys present, no route key
        class _DeadBatcher:
            prefix_cache_enabled = False

            class engine:
                tokenizer = None

            def submit_text(self, prompt, **kw):
                raise RuntimeError("decoder down")

        from docqa_tpu.service.qa import QAService

        store = _Store(["observation note for the admission"])
        qa = QAService(
            _Enc(), store, None, None, use_fake_llm=False,
            batcher=_DeadBatcher(), router=AnswerRouter(),
        )
        out = qa.ask("Why was the patient admitted?")
        assert out["degraded"] is True
        assert out["degrade_reason"] == "decoder_error"
        assert "route" not in out
        assert out["answer"]  # the extractive fallback served
