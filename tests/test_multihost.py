"""Multi-host (DCN) initialization: two real OS processes, one JAX
distributed runtime, a cross-process mesh, and a global reduction.

This is the test SURVEY §2c's "elastic / multi-node" row calls for: the
reference had no multi-node story at all (a single-host batch launcher,
``start_all.bat:12-35``), and round 2's ``multihost_init`` was an
unexercised env gate.  Here both workers join through the framework's own
``multihost_init`` (tests/multihost_worker.py), so the DCN code path in
``runtime/mesh.py`` runs for real on every CI pass — on CPU devices, the
same way every other distributed path in this suite is validated.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_and_global_reduction():
    port = _free_port()
    env = dict(os.environ)
    # 2 virtual CPU devices per process -> a 4-device global mesh
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            # generous: two JAX processes compile on a possibly-contended
            # CI core (the solo run takes ~6 s; a loaded 1-core box can
            # stretch far past 3 min)
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rc == 3 and "MULTIHOST_UNSUPPORTED" in out for rc, out, _ in outs):
        # distributed init + cross-process mesh DID come up (the worker
        # asserts both before the reduction); only the collective itself
        # is unimplemented by this jaxlib's CPU backend.  rc=3 is the
        # worker's deliberate signal for exactly that case — ANY worker
        # reporting it is decisive, because its early exit tears down the
        # coordinator and can kill the peer with an unrelated disconnect
        # error (rc=1, no marker).  A worker that completed the reduction
        # must still have produced the right sum; anything else (crash,
        # assert, wrong sum) keeps failing below.
        for _rc, out, _err in outs:
            assert "MULTIHOST_OK" not in out or "MULTIHOST_OK 6.0" in out, out
        pytest.skip(
            "jaxlib CPU backend lacks multiprocess collectives "
            "(distributed init and global mesh verified)"
        )
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        # 2 local devices/process: global sum = 2*1 + 2*2 = 6
        assert "MULTIHOST_OK 6.0" in out, out
